"""Parameter schedulers: smooth (C^1) transitions of control parameters.

Reference: namespace Schedulers (main.cpp:7805-8004).  A scheduler holds a
transition window [t0, t1] with start/end parameter sets; inside the window
values follow the cubic Hermite between the endpoints (optionally starting
with the current derivative), outside they saturate.

``LearnWaveScheduler`` is the RL bending control: parameters live on wave
coordinates c = s/L - (t - t0)/Twave, so each commanded bend travels down
the body like the curvature wave (ParameterSchedulerLearnWave,
main.cpp:7949-8002).
"""

from __future__ import annotations

import numpy as np

from cup3d_tpu.models.fish.interpolation import cubic_hermite, natural_cubic_spline


class ParameterScheduler:
    """N-parameter cubic-in-time transition (ParameterScheduler<N>)."""

    def __init__(self, npoints: int):
        self.npoints = npoints
        self.t0 = -1.0
        self.t1 = 0.0
        self.params_t0 = np.zeros(npoints)
        self.params_t1 = np.zeros(npoints)
        self.dparams_t0 = np.zeros(npoints)

    def transition(self, t, tstart, tend, params_tend,
                   use_current_derivative=False):
        """Start a transition toward params_tend (4-arg overload,
        main.cpp:7831-7845): the start values are the *current* values."""
        if t < tstart or t > tend:
            return
        params, dparams = self.get(tstart)
        self.t0 = tstart
        self.t1 = tend
        self.params_t0 = params
        self.params_t1 = np.asarray(params_tend, dtype=np.float64).copy()
        self.dparams_t0 = dparams if use_current_derivative else np.zeros(self.npoints)

    def transition_between(self, t, tstart, tend, params_tstart, params_tend):
        """5-arg overload (main.cpp:7846-7857): explicit start values;
        ignored if an earlier transition is still pending."""
        if t < tstart or t > tend:
            return
        if tstart < self.t0:
            return
        self.t0 = tstart
        self.t1 = tend
        self.params_t0 = np.asarray(params_tstart, dtype=np.float64).copy()
        self.params_t1 = np.asarray(params_tend, dtype=np.float64).copy()

    def get(self, t):
        """(params, dparams/dt) at time t (gimmeValues, main.cpp:7858-7872)."""
        if t < self.t0 or self.t0 < 0:
            return self.params_t0.copy(), np.zeros(self.npoints)
        if t > self.t1:
            return self.params_t1.copy(), np.zeros(self.npoints)
        y, dy = cubic_hermite(
            self.t0, self.t1, t, self.params_t0, self.params_t1, self.dparams_t0, 0.0
        )
        return y, dy

    def save_state(self) -> dict:
        return {
            "t0": self.t0, "t1": self.t1,
            "params_t0": self.params_t0.tolist(),
            "params_t1": self.params_t1.tolist(),
            "dparams_t0": self.dparams_t0.tolist(),
        }

    def load_state(self, d: dict) -> None:
        self.t0, self.t1 = d["t0"], d["t1"]
        self.params_t0 = np.asarray(d["params_t0"])
        self.params_t1 = np.asarray(d["params_t1"])
        self.dparams_t0 = np.asarray(d["dparams_t0"])


class ScalarScheduler(ParameterScheduler):
    """Single-parameter convenience (ParameterSchedulerScalar)."""

    def __init__(self):
        super().__init__(1)

    def transition_scalar(self, t, tstart, tend, val_start, val_end):
        self.transition_between(t, tstart, tend, [val_start], [val_end])

    def get_scalar(self, t):
        p, dp = self.get(t)
        return float(p[0]), float(dp[0])


class VectorScheduler(ParameterScheduler):
    """Spatially-distributed parameters: N control points -> values on the
    fine midline grid via natural cubic spline in s, cubic Hermite in time
    (ParameterSchedulerVector, main.cpp:7904-7948)."""

    def get_fine(self, t, positions, s_fine):
        p0 = natural_cubic_spline(positions, self.params_t0, s_fine)
        p1 = natural_cubic_spline(positions, self.params_t1, s_fine)
        dp0 = natural_cubic_spline(positions, self.dparams_t0, s_fine)
        if t < self.t0 or self.t0 < 0:
            return p0, np.zeros_like(p0)
        if t > self.t1:
            return p1, np.zeros_like(p1)
        return cubic_hermite(self.t0, self.t1, t, p0, p1, dp0, 0.0)


class LearnWaveScheduler(ParameterScheduler):
    """RL bending control riding the traveling wave.

    Values are interpolated at wave coordinate c = s/L - (t - t0)/Twave over
    the control points; outside the control range the end values extend
    flat.  ``turn`` shifts history down the body and inserts a new bend
    (ParameterSchedulerLearnWave::Turn, main.cpp:7994-8001).
    """

    def get_fine(self, t, twave, length, positions, s_fine):
        positions = np.asarray(positions, dtype=np.float64)
        c = np.asarray(s_fine) / length - (t - self.t0) / twave
        vals = np.zeros_like(c)
        dvals = np.zeros_like(c)
        below = c < positions[0]
        above = c > positions[-1]
        mid = ~(below | above)
        vals[below] = self.params_t0[0]
        vals[above] = self.params_t0[-1]
        if np.any(mid):
            cm = c[mid]
            j = np.clip(np.searchsorted(positions, cm, side="left"), 1,
                        len(positions) - 1)
            y, dy = cubic_hermite(
                positions[j - 1], positions[j], cm,
                self.params_t0[j - 1], self.params_t0[j],
            )
            vals[mid] = y
            dvals[mid] = -dy / twave  # chain rule: dc/dt = -1/Twave
        return vals, dvals

    def turn(self, b: float, t_turn: float) -> None:
        self.t0 = t_turn
        self.params_t0[2:] = self.params_t0[:-2]
        self.params_t0[1] = b
        self.params_t0[0] = 0.0
