"""Carangiform curvature kinematics: the swimming gait generator.

Reference: CurvatureDefinedFishData (main.cpp:8979-9088, computeMidline
15463-15519, performPitchingMotion 15521-15571, recomputeNormalVectors
15572-15667, execute 15434-15462).

The midline curvature is a baseline amplitude envelope (natural cubic spline
through 6 control points growing toward the tail) times a traveling wave
sin(2 pi ((t - t0)/Tp + timeshift) + pi phi - 2 pi s/(L lambda)), plus RL
bending and PID corrections:

- alpha/dalpha: amplitude modulation from streamwise-position error;
- beta/dbeta:   additive curvature from lateral-position + yaw error;
- gamma/dgamma: pitching (bending out of plane) from depth error, applied as
  a cylinder-wrap of the computed midline (performPitchingMotion);
- rlBendingScheduler: RL turn commands riding the wave;
- period/torsion schedulers for RL period and torsion actions.
"""

from __future__ import annotations

import numpy as np

from cup3d_tpu.models.fish.frenet import frenet_solve
from cup3d_tpu.models.fish.midline import FishMidlineData
from cup3d_tpu.models.fish.schedulers import (
    LearnWaveScheduler,
    ScalarScheduler,
    VectorScheduler,
)


class CurvatureDefinedFishData(FishMidlineData):
    def __init__(self, length, Tperiod, phase_shift, h, amplitude_factor=1.0):
        super().__init__(length, Tperiod, phase_shift, h, amplitude_factor)
        # PID / RL state (main.cpp:8981-9007)
        self.lastTact = 0.0
        self.lastCurv = 0.0
        self.oldrCurv = 0.0
        self.periodPIDval = self.Tperiod
        self.periodPIDdif = 0.0
        self.TperiodPID = False
        self.lastTime = 0.0
        self.time0 = 0.0
        self.timeshift = 0.0
        self.alpha, self.dalpha = 1.0, 0.0
        self.beta, self.dbeta = 0.0, 0.0
        self.gamma, self.dgamma = 0.0, 0.0
        self.curvatureScheduler = VectorScheduler(6)
        self.rlBendingScheduler = LearnWaveScheduler(7)
        self.periodScheduler = ScalarScheduler()
        # seed with Tperiod so a first call at t > 0.1 Tperiod is well-posed
        # (the reference relies on computeMidline being called from t=0)
        self.periodScheduler.params_t0[:] = self.Tperiod
        self.periodScheduler.params_t1[:] = self.Tperiod
        self.control_torsion = False
        self.torsionScheduler = VectorScheduler(3)
        self.torsionValues = np.zeros(3)
        self.torsionValues_previous = np.zeros(3)
        self.Ttorsion_start = 0.0
        self.current_period = self.Tperiod
        self.next_period = self.Tperiod
        self.transition_start = 0.0
        self.transition_duration = 0.1 * self.Tperiod

    # -- RL actions (execute, main.cpp:15434-15462) ------------------------

    def execute(self, time: float, l_tnext: float, action) -> None:
        action = np.atleast_1d(np.asarray(action, dtype=np.float64))
        if len(action) >= 1:
            self.rlBendingScheduler.turn(float(action[0]), l_tnext)
        if len(action) in (3, 5):
            self.current_period = self.periodPIDval
            self.next_period = self.Tperiod * (1 + float(action[1]))
            self.transition_start = l_tnext
        if len(action) == 5:
            self.torsionValues_previous = self.torsionValues.copy()
            self.torsionValues = action[2:5].copy()
            self.Ttorsion_start = time

    def correct_tail_period(self, period_fac, period_vel, t, dt):
        """PID tail-beat period modulation (main.cpp:9030-9043).

        Note a deliberate divergence: the condensed reference defines
        correctTailPeriod but never calls it, and its computeMidline
        unconditionally overwrites periodPIDval from the period scheduler
        (main.cpp:15467) — the API is dead there.  Here compute_midline
        skips the scheduler overwrite while TperiodPID is active, so this
        control entry point actually works (upstream CubismUP_3D behavior).
        """
        last_arg = (self.lastTime - self.time0) / self.periodPIDval + self.timeshift
        self.time0 = self.lastTime
        self.timeshift = last_arg
        self.periodPIDval = self.Tperiod * period_fac
        self.periodPIDdif = self.Tperiod * period_vel
        self.lastTime = t
        self.TperiodPID = True

    # -- gait -------------------------------------------------------------

    def compute_midline(self, t: float, dt: float) -> None:
        L = self.length
        self.periodScheduler.transition_scalar(
            t, self.transition_start,
            self.transition_start + self.transition_duration,
            self.current_period, self.next_period,
        )
        if not self.TperiodPID:  # PID takeover holds the period (see
            # correct_tail_period); otherwise the scheduler drives it
            self.periodPIDval, self.periodPIDdif = self.periodScheduler.get_scalar(t)
        if self.transition_start < t < self.transition_start + self.transition_duration:
            self.timeshift = (t - self.time0) / self.periodPIDval + self.timeshift
            self.time0 = t

        curvature_points = np.array([0.0, 0.15, 0.4, 0.65, 0.9, 1.0]) * L
        bend_points = np.array([-0.5, -0.25, 0.0, 0.25, 0.5, 0.75, 1.0])
        curvature_values = (
            np.array([0.82014, 1.46515, 2.57136, 3.75425, 5.09147, 5.70449]) / L
        )
        # amplitude ramps 0 -> baseline over the first period (15480-15483)
        self.curvatureScheduler.transition_between(
            0.0, 0.0, self.Tperiod, np.zeros(6), curvature_values
        )
        rC, vC = self.curvatureScheduler.get_fine(t, curvature_points, self.rS)
        rB, vB = self.rlBendingScheduler.get_fine(
            t, self.periodPIDval, L, bend_points, self.rS
        )

        diffT = (
            1.0 - (t - self.time0) * self.periodPIDdif / self.periodPIDval
            if self.TperiodPID
            else 1.0
        )
        darg = 2.0 * np.pi / self.periodPIDval * diffT
        arg0 = (
            2.0 * np.pi * ((t - self.time0) / self.periodPIDval + self.timeshift)
            + np.pi * self.phaseShift
        )
        arg = arg0 - 2.0 * np.pi * self.rS / (L * self.waveLength)
        curv = np.sin(arg) + rB + self.beta
        dcurv = np.cos(arg) * darg + vB + self.dbeta
        af = self.amplitudeFactor
        rK = self.alpha * af * rC * curv
        vK = self.alpha * af * (vC * curv + rC * dcurv) + self.dalpha * af * rC * curv
        if not np.all(np.isfinite(rK)) or not np.all(np.isfinite(vK)):
            raise FloatingPointError("non-finite midline curvature")

        rT = np.zeros(self.Nm)
        vT = np.zeros(self.Nm)
        if self.control_torsion:
            torsion_points = np.array([0.0, 0.5, 1.0]) * L
            self.torsionScheduler.transition_between(
                t, self.Ttorsion_start, self.Ttorsion_start + 0.5 * self.Tperiod,
                self.torsionValues_previous, self.torsionValues,
            )
            rT, vT = self.torsionScheduler.get_fine(t, torsion_points, self.rS)

        sol = frenet_solve(self.rS, rK, vK, rT, vT)
        self.r, self.v = sol["r"], sol["v"]
        self.nor, self.vnor = sol["nor"], sol["vnor"]
        self.bin, self.vbin = sol["bin"], sol["vbin"]
        self.perform_pitching_motion(t)

    def perform_pitching_motion(self, t: float) -> None:
        """Wrap the planar midline onto a cylinder of radius 1/gamma for
        depth control (main.cpp:15521-15571)."""
        if abs(self.gamma) > 1e-10:
            R = 1.0 / self.gamma
            Rdot = -self.dgamma / self.gamma**2
        else:
            R = 1e10 if self.gamma >= 0 else -1e10
            Rdot = 0.0
        x0N, y0N = self.r[-1, 0], self.r[-1, 1]
        x0Nd, y0Nd = self.v[-1, 0], self.v[-1, 1]
        phi = np.arctan2(y0N, x0N)
        phidot = (y0Nd / x0N - y0N * x0Nd / x0N**2) / (1.0 + (y0N / x0N) ** 2)
        M = np.hypot(x0N, y0N)
        Mdot = (x0N * x0Nd + y0N * y0Nd) / M
        cphi, sphi = np.cos(phi), np.sin(phi)

        x0, y0 = self.r[:, 0], self.r[:, 1]
        x0d, y0d = self.v[:, 0], self.v[:, 1]
        x1 = cphi * x0 - sphi * y0
        y1 = sphi * x0 + cphi * y0
        x1d = cphi * x0d - sphi * y0d + (-sphi * x0 - cphi * y0) * phidot
        y1d = sphi * x0d + cphi * y0d + (cphi * x0 - sphi * y0) * phidot
        theta = (M - x1) / R
        cth, sth = np.cos(theta), np.sin(theta)
        thetad = (Mdot - x1d) / R - (M - x1) / R**2 * Rdot
        self.r = np.stack([M - R * sth, y1, R - R * cth], axis=1)
        self.v = np.stack(
            [
                Mdot - Rdot * sth - R * cth * thetad,
                y1d,
                Rdot - Rdot * cth + R * sth * thetad,
            ],
            axis=1,
        )
        self.recompute_normal_vectors()

    def recompute_normal_vectors(self) -> None:
        """Re-orthonormalize nor/bin (+ their velocities) against the
        recomputed tangent after pitching (main.cpp:15572-15667)."""
        nm = self.Nm
        rs = self.rS
        t_vec = np.empty((nm, 3))
        dt_vec = np.empty((nm, 3))
        # nonuniform-grid one-sided-weights tangent in the interior
        hp = (rs[2:] - rs[1:-1])[:, None]
        hm = (rs[1:-1] - rs[:-2])[:, None]
        frac = hp / hm
        am, a, ap = -frac * frac, frac * frac - 1.0, np.ones_like(frac)
        denom = 1.0 / (hp * (1.0 + frac))
        t_vec[1:-1] = (am * self.r[:-2] + a * self.r[1:-1] + ap * self.r[2:]) * denom
        dt_vec[1:-1] = (am * self.v[:-2] + a * self.v[1:-1] + ap * self.v[2:]) * denom
        # ends: two-point slopes toward the interior
        for i, ipm in ((0, 1), (nm - 1, nm - 2)):
            ids = 1.0 / (rs[ipm] - rs[i])
            t_vec[i] = (self.r[ipm] - self.r[i]) * ids
            dt_vec[i] = (self.v[ipm] - self.v[i]) * ids

        # Gram-Schmidt nor against tangent, carrying time derivatives
        dot = np.einsum("ij,ij->i", self.nor, t_vec)[:, None]
        ddot = (
            np.einsum("ij,ij->i", self.vnor, t_vec)
            + np.einsum("ij,ij->i", self.nor, dt_vec)
        )[:, None]
        nor = self.nor - dot * t_vec
        inorm = 1.0 / np.linalg.norm(nor, axis=1, keepdims=True)
        self.nor = nor * inorm
        self.vnor = self.vnor - ddot * t_vec - dot * dt_vec
        bin_ = np.cross(t_vec, self.nor)
        ibnorm = 1.0 / np.linalg.norm(bin_, axis=1, keepdims=True)
        self.bin = bin_ * ibnorm
        self.vbin = np.cross(dt_vec, self.nor) + np.cross(t_vec, self.vnor)
