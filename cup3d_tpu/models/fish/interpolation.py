"""1-D interpolation primitives for the fish kinematics (host, NumPy).

Reference: Interpolation1D (main.cpp:7732-7804) -- natural cubic spline and
a two-point cubic Hermite that also returns the derivative.  Vectorized over
evaluation points instead of the reference's per-point binary search.
"""

from __future__ import annotations

import numpy as np


def natural_cubic_spline(x: np.ndarray, y: np.ndarray, xq: np.ndarray) -> np.ndarray:
    """Natural cubic spline through (x, y), evaluated at xq.

    Natural BCs: second derivative zero at both ends
    (main.cpp:7739-7770 semantics).  Query points are clamped to [x0, xn]
    segments but extrapolate with the end cubics, as the reference does.
    """
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    n = len(x)
    # tridiagonal solve for second derivatives y2 (Thomas algorithm)
    y2 = np.zeros(n)
    u = np.zeros(n)
    for i in range(1, n - 1):
        sig = (x[i] - x[i - 1]) / (x[i + 1] - x[i - 1])
        p = sig * y2[i - 1] + 2.0
        y2[i] = (sig - 1.0) / p
        du = (y[i + 1] - y[i]) / (x[i + 1] - x[i]) - (y[i] - y[i - 1]) / (
            x[i] - x[i - 1]
        )
        u[i] = (6.0 * du / (x[i + 1] - x[i - 1]) - sig * u[i - 1]) / p
    for k in range(n - 2, 0, -1):
        y2[k] = y2[k] * y2[k + 1] + u[k]

    xq = np.asarray(xq, dtype=np.float64)
    klo = np.clip(np.searchsorted(x, xq, side="right") - 1, 0, n - 2)
    khi = klo + 1
    h = x[khi] - x[klo]
    a = (x[khi] - xq) / h
    b = (xq - x[klo]) / h
    return (
        a * y[klo]
        + b * y[khi]
        + ((a**3 - a) * y2[klo] + (b**3 - b) * y2[khi]) * (h * h) / 6.0
    )


def cubic_hermite(x0, x1, x, y0, y1, dy0=0.0, dy1=0.0):
    """Cubic with endpoint values/derivatives; returns (y, dy/dx).

    Matches Interpolation1D::cubicInterpolation (main.cpp:7780-7795);
    vectorized in any of the arguments.
    """
    xr = np.asarray(x) - x0
    dx = x1 - x0
    a = (dy0 + dy1) / (dx * dx) - 2.0 * (y1 - y0) / (dx * dx * dx)
    b = (-2.0 * dy0 - dy1) / dx + 3.0 * (y1 - y0) / (dx * dx)
    c = dy0
    d = y0
    y = a * xr**3 + b * xr**2 + c * xr + d
    dy = 3.0 * a * xr**2 + 2.0 * b * xr + c
    return y, dy
