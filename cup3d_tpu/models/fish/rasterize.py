"""Midline -> (SDF, udef) rasterization: the device-side half of the fish.

Reference: PutFishOnBlocks (main.cpp:8212-8291, 11350-11739) marches surface
points per cross-section and scatters distances into per-block SDF arrays.
That shape is hostile to TPUs (data-dependent scatter, ragged work).  Here
the same geometry -- a tube of elliptical cross-sections along the midline,
semi-axis `width` along the normal and `height` along the binormal -- is
evaluated as a *gather*: every cell of a dense window computes its signed
distance to all midline segments with a `lax.fori_loop` over segments of
fused elementwise ops, taking the union (min) of per-segment signed
distances.  The deformation velocity at a cell is the reference's formula
udef = v + u * vNor + w * vBin at the plane offsets (u, w) of the cell in
the closest cross-section (surface-clamped outside, main.cpp:11476-11487
and 11677-11680).

Sign convention: sdf > 0 inside the body (as the reference's SDFLAB after
signedDistanceSqrt, main.cpp:11718-11739).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# body-frame rotations are position-critical: at this JAX build's default
# bf16-grade matmul precision the rotated coordinates carry ~1e-2 relative
# error, which exceeds the SDF scale of a thin fish section (the sharp
# Towers chi then loses every interior cell ON TPU while CPU runs are
# fine) — every geometric einsum here pins HIGHEST precision
_HI = jax.lax.Precision.HIGHEST

_WEPS = 1e-10  # degenerate-section guard (reference: width,height >= 1e-10)


def _segment_distance(p, seg):
    """Signed distance (+outside) of points p (..., 3) to one elliptical
    cone segment, and the plane coordinates needed for udef.

    seg: dict of endpoint-pair arrays r0,r1 (3,), nor0,nor1, bin0,bin1,
    v0,v1, vnor0,vnor1, vbin0,vbin1, w0,w1, h0,h1 (scalars).
    """
    a = seg["r1"] - seg["r0"]
    alen2 = jnp.maximum(jnp.dot(a, a), 1e-30)
    delta = p - seg["r0"]
    t_raw = jnp.einsum("...c,c->...", delta, a, precision=_HI) / alen2
    t = jnp.clip(t_raw, 0.0, 1.0)
    # axial excess beyond the segment span, in physical length
    ax = (t_raw - t) * jnp.sqrt(alen2)

    def lerp(x0, x1):
        return x0 + t[..., None] * (x1 - x0) if jnp.ndim(x0) else x0 + t * (x1 - x0)

    rm = seg["r0"] + t[..., None] * (seg["r1"] - seg["r0"])
    nor = seg["nor0"] + t[..., None] * (seg["nor1"] - seg["nor0"])
    bn = seg["bin0"] + t[..., None] * (seg["bin1"] - seg["bin0"])
    w = jnp.maximum(lerp(seg["w0"], seg["w1"]), _WEPS)
    hh = jnp.maximum(lerp(seg["h0"], seg["h1"]), _WEPS)

    d2 = p - rm
    u = jnp.einsum("...c,...c->...", d2, nor, precision=_HI)
    v = jnp.einsum("...c,...c->...", d2, bn, precision=_HI)
    q = jnp.sqrt((u / w) ** 2 + (v / hh) ** 2 + 1e-30)
    # first-order signed distance to the ellipse: f/|grad f| with f = q - 1.
    # |grad f| = hypot(u/w^2, v/h^2)/q is computed via the *unit* direction
    # (t1, t2) = (u/w, v/h)/q so nothing divides by w^2/h^2 directly: at the
    # degenerate tip sections (w = h = 1e-10) u/w^2 overflows float32 to
    # inf, which used to zero the in-plane distance and mark far-field
    # cells as near-surface (spurious chi bands across the whole domain).
    t1 = (u / w) / q
    t2 = (v / hh) / q
    inv_ratio = jnp.sqrt((t1 / w) ** 2 + (t2 / hh) ** 2 + 1e-30)
    # infimum of |grad f| over directions is 1/max(w, h): floor it so the
    # exactly-on-axis case stays at the physical depth scale
    inv_ratio = jnp.maximum(inv_ratio, 1.0 / jnp.maximum(w, hh))
    # f/|grad f| is accurate only near the surface; for eccentric sections
    # it underestimates far-field distance by the axis ratio (the thin
    # tail would paint spurious near-surface bands across the domain).
    # hypot(u, v) - max(w, h) is a rigorous lower bound everywhere (point
    # distance to the section's bounding circle), exact in the far field:
    # take the larger of the two (both are lower bounds outside; inside,
    # the bound is positive only if the point is provably outside)
    d_plane = jnp.maximum(
        (q - 1.0) / inv_ratio,
        jnp.hypot(u, v) - jnp.maximum(w, hh),
    )
    ax_abs = jnp.abs(ax)
    d_signed = jnp.where(
        ax_abs > 0.0, jnp.hypot(jnp.maximum(d_plane, 0.0), ax_abs), d_plane
    )

    # deformation velocity, plane offsets clamped to the surface outside
    scale = jnp.minimum(1.0, 1.0 / q)[..., None]
    vmid = seg["v0"] + t[..., None] * (seg["v1"] - seg["v0"])
    vnor = seg["vnor0"] + t[..., None] * (seg["vnor1"] - seg["vnor0"])
    vbin = seg["vbin0"] + t[..., None] * (seg["vbin1"] - seg["vbin0"])
    udef = vmid + scale * (u[..., None] * vnor + v[..., None] * vbin)
    return d_signed, udef


@jax.jit
def rasterize_points(points, midline, position, rot):
    """Rasterize a midline tube at arbitrary cell centers.

    The layout-generic core shared by the dense uniform window and the
    per-candidate-block AMR path (the TPU analogue of the reference's
    per-block PutFishOnBlocks, main.cpp:10718-10951).

    Args:
      points: (..., 3) computational-frame cell-center coordinates.
      midline: dict of device arrays r, v, nor, vnor, bin, vbin (Nm, 3)
        and width, height (Nm,) -- body frame.
      position: (3,) body position in the computational frame.
      rot: (3, 3) body->computational rotation matrix.

    Returns (sdf, udef): points.shape[:-1] with sdf > 0 inside, and
    (..., 3) deformation velocity in the computational frame.
    """
    dtype = midline["r"].dtype
    # body frame: x_body = R^T (x_comp - position)
    p = jnp.einsum("...c,cd->...d", points - position, rot, precision=_HI)
    shape = p.shape[:-1]

    nm = midline["r"].shape[0]
    big = jnp.asarray(1e10, dtype)
    d0 = jnp.full(shape, big)
    u0 = jnp.zeros(shape + (3,), dtype)

    def body(ss, carry):
        dmin, udef = carry
        seg = {}
        for name, key in (("r", "r"), ("v", "v"), ("nor", "nor"),
                          ("vnor", "vnor"), ("bin", "bin"), ("vbin", "vbin")):
            arr = midline[key]
            seg[name + "0"] = jax.lax.dynamic_slice(arr, (ss, 0), (1, 3))[0]
            seg[name + "1"] = jax.lax.dynamic_slice(arr, (ss + 1, 0), (1, 3))[0]
        for name, key in (("w", "width"), ("h", "height")):
            arr = midline[key]
            seg[name + "0"] = jax.lax.dynamic_slice(arr, (ss,), (1,))[0]
            seg[name + "1"] = jax.lax.dynamic_slice(arr, (ss + 1,), (1,))[0]
        d, ud = _segment_distance(p, seg)
        closer = d < dmin
        return jnp.minimum(d, dmin), jnp.where(closer[..., None], ud, udef)

    dmin, udef_body = jax.lax.fori_loop(0, nm - 1, body, (d0, u0))
    sdf = -dmin  # reference convention: positive inside
    udef_comp = jnp.einsum("...c,dc->...d", udef_body, rot, precision=_HI)
    return sdf, udef_comp


@partial(jax.jit, static_argnames=("window_shape",))
def rasterize_midline(
    origin,
    h,
    window_shape,
    midline,
    position,
    rot,
):
    """Rasterize a midline tube over a dense uniform window.

    Args:
      origin: (3,) physical coordinate of the window corner (device).
      h: cell spacing (python float or scalar).
      window_shape: static (nx, ny, nz) of the window.
      midline / position / rot: see rasterize_points.

    Returns (sdf, udef): (nx,ny,nz) with sdf > 0 inside, and (nx,ny,nz,3)
    deformation velocity in the computational frame.
    """
    nx, ny, nz = window_shape
    dtype = midline["r"].dtype
    ii = jnp.arange(nx, dtype=dtype)
    jj = jnp.arange(ny, dtype=dtype)
    kk = jnp.arange(nz, dtype=dtype)
    X = origin[0] + (ii[:, None, None] + 0.5) * h
    Y = origin[1] + (jj[None, :, None] + 0.5) * h
    Z = origin[2] + (kk[None, None, :] + 0.5) * h
    p_comp = jnp.stack(jnp.broadcast_arrays(X, Y, Z), axis=-1)
    return rasterize_points(p_comp, midline, position, rot)
