"""Width/height profiles of the fish cross-sections.

Reference: MidlineShapes (main.cpp:11927-12198).  Profiles are functions of
arc length s in [0, L] giving the half-width (along the normal) and
half-height (along the binormal) of the elliptical cross-section:

- analytic piecewise profiles: ``stefan``, ``larval``, ``danio``, ``nacaNN``;
- B-spline control-polygon profiles: ``baseline`` (default), ``fatter``,
  ``largefin``, ``tunaclone`` -- a parametric cubic B-spline (x(t), y(t))
  through control points, evaluated at x = s.  The reference uses GSL's
  uniform-knot cubic bspline (integrateBSpline, main.cpp:11927-11964); here
  the same clamped-uniform-knot basis is built with a vectorized Cox-de Boor
  recursion and the curve is sampled densely then inverted with interp.
"""

from __future__ import annotations

import numpy as np


def _bspline_basis(t, knots, order):
    """Cox-de Boor: basis values for all n functions at points t.

    Returns (len(t), n) with n = len(knots) - order.
    """
    t = np.atleast_1d(np.asarray(t, dtype=np.float64))
    n = len(knots) - order
    # degree-0: indicator of [knots[j], knots[j+1]) (last interval closed)
    b = np.zeros((len(t), len(knots) - 1))
    for j in range(len(knots) - 1):
        if knots[j + 1] > knots[j]:
            b[:, j] = (t >= knots[j]) & (t < knots[j + 1])
    b[t >= knots[-1] - 1e-14, np.max(np.nonzero(np.diff(knots))[0])] = 1.0
    for k in range(1, order):
        nb = np.zeros((len(t), len(knots) - 1 - k))
        for j in range(len(knots) - 1 - k):
            d1 = knots[j + k] - knots[j]
            d2 = knots[j + k + 1] - knots[j + 1]
            term = 0.0
            if d1 > 0:
                term = (t - knots[j]) / d1 * b[:, j]
            if d2 > 0:
                term = term + (knots[j + k + 1] - t) / d2 * b[:, j + 1]
            nb[:, j] = term
        b = nb
    return b[:, :n]


def bspline_profile(xc, yc, length, rs, nsamples=4096):
    """Parametric clamped-uniform cubic B-spline through control points
    (xc, yc); returns profile(s) = y at x = s, 0 outside (0, L)."""
    xc = np.asarray(xc, dtype=np.float64)
    yc = np.asarray(yc, dtype=np.float64)
    n = len(xc)
    # chord length parameterization bound, as the reference (11932-11935)
    clen = float(np.sum(np.hypot(np.diff(xc), np.diff(yc))))
    # GSL: order 4, nbreak = n-2 uniform breakpoints -> clamped knots
    order = 4
    interior = np.linspace(0.0, clen, n - 2)
    knots = np.concatenate([[0.0] * (order - 1), interior, [clen] * (order - 1)])
    t = np.linspace(0.0, clen, nsamples)
    basis = _bspline_basis(t, knots, order)
    xs = basis @ xc
    ys = basis @ yc
    # x(t) is monotone for these control polygons; invert by interpolation
    order_idx = np.argsort(xs)
    xs, ys = xs[order_idx], ys[order_idx]
    res = np.interp(rs, xs, ys)
    res = np.where((rs > 0) & (rs < length), res, 0.0)
    return res


def naca_width(t_ratio, length, rs):
    """Symmetric 4-digit NACA half-thickness (main.cpp:11965-11983)."""
    a, b, c, d, e = 0.2969, -0.1260, -0.3516, 0.2843, -0.1015
    t = t_ratio * length
    p = np.clip(rs / length, 0.0, 1.0)
    w = 5 * t * (a * np.sqrt(p) + b * p + c * p**2 + d * p**3 + e * p**4)
    return np.where((rs > 0) & (rs < length), w, 0.0)


def stefan_width(length, rs):
    """(main.cpp:11984-12001)"""
    L = length
    sb, st, wt, wh = 0.04 * L, 0.95 * L, 0.01 * L, 0.04 * L
    s = rs
    w = np.where(
        s < sb,
        np.sqrt(np.maximum(2.0 * wh * s - s * s, 0.0)),
        np.where(
            s < st,
            wh - (wh - wt) * ((s - sb) / (st - sb)) ** 2,
            wt * (L - s) / (L - st),
        ),
    )
    return np.where((rs > 0) & (rs < length), w, 0.0)


def stefan_height(length, rs):
    """(main.cpp:12002-12014)"""
    L = length
    a, b = 0.51 * L, 0.08 * L
    w = b * np.sqrt(np.maximum(1.0 - ((rs - a) / a) ** 2, 0.0))
    return np.where((rs > 0) & (rs < length), w, 0.0)


def larval_width(length, rs):
    """(main.cpp:12015-12036)"""
    L = length
    sb, st = 0.0862 * L, 0.3448 * L
    wh, wt = 0.0635 * L, 0.0254 * L
    s = rs
    x = (s - sb) / (st - sb)
    w = np.where(
        s < sb,
        wh * np.sqrt(np.maximum(1.0 - ((sb - s) / sb) ** 2, 0.0)),
        np.where(
            s < st,
            (-2 * (wt - wh) - wt * (st - sb)) * x**3
            + (3 * (wt - wh) + wt * (st - sb)) * x**2
            + wh,
            wt - wt * (s - st) / (L - st),
        ),
    )
    return np.where((rs > 0) & (rs < length), w, 0.0)


def larval_height(length, rs):
    """(main.cpp:12037-12070)"""
    L = length
    s1, h1 = 0.287 * L, 0.072 * L
    s2, h2 = 0.844 * L, 0.041 * L
    s3, h3 = 0.957 * L, 0.071 * L
    s = rs
    x12 = (s - s1) / (s2 - s1)
    x23 = (s - s2) / (s3 - s2)
    w = np.where(
        s < s1,
        h1 * np.sqrt(np.maximum(1.0 - ((s - s1) / s1) ** 2, 0.0)),
        np.where(
            s < s2,
            -2 * (h2 - h1) * x12**3 + 3 * (h2 - h1) * x12**2 + h1,
            np.where(
                s < s3,
                -2 * (h3 - h2) * x23**3 + 3 * (h3 - h2) * x23**2 + h2,
                h3 * np.sqrt(np.maximum(1.0 - ((s - s3) / (L - s3)) ** 3, 0.0)),
            ),
        ),
    )
    return np.where((rs > 0) & (rs < length), w, 0.0)


def _piecewise_cubic(breaks, coeffs, length, rs):
    """Zebrafish-measurement piecewise cubics in normalized s (danio_*)."""
    sn = np.clip(rs / length, 0.0, 1.0)
    seg = np.clip(np.searchsorted(breaks, sn, side="right") - 1, 0,
                  len(breaks) - 2)
    c = np.asarray(coeffs)[seg]
    xx = sn - np.asarray(breaks)[seg]
    w = length * (c[:, 0] + c[:, 1] * xx + c[:, 2] * xx**2 + c[:, 3] * xx**3)
    return np.where((rs > 0) & (rs < length), w, 0.0)


# measured zebrafish geometry tables (main.cpp:12071-12135)
_DANIO_W_BREAKS = [0, 0.005, 0.01, 0.05, 0.1, 0.2, 0.4, 0.6, 0.8, 0.95, 1.0]
_DANIO_W_COEFFS = [
    [0.0015713, 2.6439, 0, -15410],
    [0.012865, 1.4882, -231.15, 15598],
    [0.016476, 0.34647, 2.8156, -39.328],
    [0.032323, 0.38294, -1.9038, 0.7411],
    [0.046803, 0.19812, -1.7926, 5.4876],
    [0.054176, 0.0042136, -0.14638, 0.077447],
    [0.049783, -0.045043, -0.099907, -0.12599],
    [0.03577, -0.10012, -0.1755, 0.62019],
    [0.013687, -0.0959, 0.19662, 0.82341],
    [0.0065049, 0.018665, 0.56715, -3.781],
]
_DANIO_H_BREAKS = [0, 0.01, 0.05, 0.1, 0.3, 0.5, 0.7, 0.8, 0.85, 0.87, 0.9,
                   0.993, 0.996, 0.998, 1]
_DANIO_H_COEFFS = [
    [0.0011746, 1.345, 2.2204e-14, -578.62],
    [0.014046, 1.1715, -17.359, 128.6],
    [0.041361, 0.40004, -1.9268, 9.7029],
    [0.057759, 0.28013, -0.47141, -0.08102],
    [0.094281, 0.081843, -0.52002, -0.76511],
    [0.083728, -0.21798, -0.97909, 3.9699],
    [0.032727, -0.13323, 1.4028, 2.5693],
    [0.036002, 0.22441, 2.1736, -13.194],
    [0.051007, 0.34282, 0.19446, 16.642],
    [0.058075, 0.37057, 1.193, -17.944],
    [0.069781, 0.3937, -0.42196, -29.388],
    [0.079107, -0.44731, -8.6211, -1.8283e5],
    [0.072751, -5.4355, -1654.1, -2.9121e5],
    [0.052934, -15.546, -3401.4, 5.6689e5],
]


def danio_width(length, rs):
    return _piecewise_cubic(_DANIO_W_BREAKS, _DANIO_W_COEFFS, length, rs)


def danio_height(length, rs):
    return _piecewise_cubic(_DANIO_H_BREAKS, _DANIO_H_COEFFS, length, rs)


def compute_widths_heights(height_name: str, width_name: str, length, rs):
    """Dispatcher (computeWidthsHeights, main.cpp:12136-12198).

    Returns (height, width) on the rs grid.
    """
    L = length

    def height_of(name):
        if name == "largefin":
            xh = np.array([0, 0, 0.2, 0.4, 0.6, 0.8, 1.0, 1.0]) * L
            yh = np.array([0, 0.055, 0.18, 0.2, 0.064, 0.002, 0.325, 0]) * L
            return bspline_profile(xh, yh, L, rs)
        if name == "tunaclone":
            xh = np.array([0, 0, 0.2, 0.4, 0.6, 0.9, 0.96, 1.0, 1.0]) * L
            yh = np.array([0, 0.05, 0.14, 0.15, 0.11, 0, 0.1, 0.2, 0]) * L
            return bspline_profile(xh, yh, L, rs)
        if name.startswith("naca"):
            return naca_width(int(name[4:]) * 0.01, L, rs)
        if name == "danio":
            return danio_height(L, rs)
        if name == "stefan":
            return stefan_height(L, rs)
        if name == "larval":
            return larval_height(L, rs)
        # baseline height control polygon (main.cpp:12167-12172)
        xh = np.array([0, 0, 0.2, 0.4, 0.6, 0.8, 1.0, 1.0]) * L
        yh = np.array([0, 0.055, 0.068, 0.076, 0.064, 0.0072, 0.11, 0]) * L
        return bspline_profile(xh, yh, L, rs)

    def width_of(name):
        if name == "fatter":
            xw = np.array([0, 0, 1 / 3, 2 / 3, 1.0, 1.0]) * L
            yw = np.array([0, 8.9e-2, 7.0e-2, 3.0e-2, 2.0e-2, 0]) * L
            return bspline_profile(xw, yw, L, rs)
        if name.startswith("naca"):
            return naca_width(int(name[4:]) * 0.01, L, rs)
        if name == "danio":
            return danio_width(L, rs)
        if name == "stefan":
            return stefan_width(L, rs)
        if name == "larval":
            return larval_width(L, rs)
        # baseline width control polygon (main.cpp:12188-12193)
        xw = np.array([0, 0, 1 / 3, 2 / 3, 1.0, 1.0]) * L
        yw = np.array([0, 8.9e-2, 1.7e-2, 1.6e-2, 1.3e-2, 0]) * L
        return bspline_profile(xw, yw, L, rs)

    return height_of(height_name), width_of(width_name)
