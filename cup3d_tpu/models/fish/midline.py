"""FishMidlineData: the discretized deforming midline (host, NumPy).

Reference: FishMidlineData (main.cpp:8005-8194) + momentum-removal integrals
(main.cpp:10961-11219).  Holds the arc-length grid rS (refined near nose and
tail), the Frenet frame r/nor/bin with time derivatives, and the cross-
section width/height profiles.  After each ``compute_midline`` the midline is
shifted/rotated so its *deformation* carries zero linear and angular momentum
-- the body-frame correction that makes swimming forces come out of the
fluid coupling, not the prescribed kinematics.
"""

from __future__ import annotations

import numpy as np


def _d_ds(rs: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """One-sided ends, averaged-slope interior derivative d(vals)/ds
    (main.cpp:8050-8059); vals is (Nm,) or (Nm, 3)."""
    out = np.empty_like(vals)
    ds = np.diff(rs)
    if vals.ndim == 2:
        ds = ds[:, None]
    fwd = (vals[1:] - vals[:-1]) / ds
    out[0] = fwd[0]
    out[-1] = fwd[-1]
    out[1:-1] = 0.5 * (fwd[1:] + fwd[:-1])
    return out


def midline_arc_grid(length: float, h: float):
    """Arc-length grid with refined ends (ctor, main.cpp:8078-8091).

    10% of the length at each end uses spacing ramping from 0.125h to
    h/sqrt(3); the middle 80% is uniform at h/sqrt(3).
    """
    frac_refined = 0.1
    frac_mid = 1.0 - 2 * frac_refined
    ds_mid_tgt = h / np.sqrt(3.0)
    ds_refine_tgt = 0.125 * h
    nmid = int(np.ceil(length * frac_mid / ds_mid_tgt / 8)) * 8
    ds_mid = length * frac_mid / nmid
    nend = int(np.ceil(frac_refined * length * 2 / (ds_mid + ds_refine_tgt) / 4)) * 4
    ds_ref = frac_refined * length * 2 / nend - ds_mid
    nm = nmid + 2 * nend + 1

    # guard for very coarse h, where the reference formula degenerates to
    # ds_ref <= 0 (duplicate points): keep a strictly positive ramp start
    ds_ref = max(ds_ref, 0.25 * ds_refine_tgt)

    rs = np.zeros(nm)
    k = 0
    for i in range(nend):
        rs[k + 1] = rs[k] + ds_ref + (ds_mid - ds_ref) * i / (nend - 1.0)
        k += 1
    for _ in range(nmid):
        rs[k + 1] = rs[k] + ds_mid
        k += 1
    for i in range(nend):
        rs[k + 1] = rs[k] + ds_ref + (ds_mid - ds_ref) * (nend - i - 1) / (nend - 1.0)
        k += 1
    # normalize so the midline spans exactly [0, L] and stays strictly
    # monotone even after the ds_ref guard
    rs *= length / rs[k]
    return rs


class FishMidlineData:
    """Midline state: geometry r, velocity v, frames nor/bin + derivatives,
    profiles width/height, internal-rotation quaternion."""

    def __init__(self, length, Tperiod, phase_shift, h, amplitude_factor=1.0):
        self.length = float(length)
        self.Tperiod = float(Tperiod)
        self.phaseShift = float(phase_shift)
        self.h = float(h)
        self.amplitudeFactor = float(amplitude_factor)
        self.waveLength = 1.0

        self.rS = midline_arc_grid(length, h)
        self.Nm = len(self.rS)
        z3 = lambda: np.zeros((self.Nm, 3))
        self.r, self.v = z3(), z3()
        self.nor, self.vnor = z3(), z3()
        self.bin, self.vbin = z3(), z3()
        self.width = np.zeros(self.Nm)
        self.height = np.zeros(self.Nm)
        # body-frame correction state (main.cpp:8045-8046)
        self.quaternion_internal = np.array([1.0, 0.0, 0.0, 0.0])
        self.angvel_internal = np.zeros(3)
        # 3 sensor points: nose, upper, lower (main.cpp:8044, filled by
        # the rasterizer in the reference, by StefanFish here)
        self.sensorLocation = np.zeros(9)

    def compute_midline(self, t: float, dt: float) -> None:
        raise NotImplementedError

    # -- deformation-momentum removal -------------------------------------

    def _section_integrals(self):
        """Common factors of the elliptic-section volume integrals.

        A cross-section at arc position s is an ellipse with semi-axes
        width (along nor) and height (along bin); the volume element
        follows the reference's first-order expansion in the frame
        derivatives (main.cpp:10961-10995).
        Returns (ds, cR, cN, cB, m00, m11, m22): trapezoid arc weights, the
        volume-normal (nor x bin) projected onto d(r,nor,bin)/ds, and the
        elliptic-section moments w*H, w^3*H/4, w*H^3/4.
        """
        rs = self.rS
        ds = np.empty(self.Nm)
        ds[0] = 0.5 * (rs[1] - rs[0])
        ds[-1] = 0.5 * (rs[-1] - rs[-2])
        ds[1:-1] = 0.5 * (rs[2:] - rs[:-2])
        c = np.cross(self.nor, self.bin)
        drds = _d_ds(rs, self.r)
        dnds = _d_ds(rs, self.nor)
        dbds = _d_ds(rs, self.bin)
        w, H = self.width, self.height
        m00 = w * H
        m11 = 0.25 * w**3 * H
        m22 = 0.25 * w * H**3
        cR = np.einsum("ij,ij->i", c, drds)
        cN = np.einsum("ij,ij->i", c, dnds)
        cB = np.einsum("ij,ij->i", c, dbds)
        return ds, cR, cN, cB, m00, m11, m22

    def integrate_linear_momentum(self) -> None:
        """Shift r and v so the deforming body has zero net volume-weighted
        position and linear momentum (main.cpp:10961-11012)."""
        ds, cR, cN, cB, m00, m11, m22 = self._section_integrals()
        aux1 = m00 * cR * ds
        aux2 = m11 * cN * ds
        aux3 = m22 * cB * ds
        vol = np.sum(aux1) * np.pi
        cm = (
            np.einsum("i,ij->j", aux1, self.r)
            + np.einsum("i,ij->j", aux2, self.nor)
            + np.einsum("i,ij->j", aux3, self.bin)
        ) * np.pi / vol
        lm = (
            np.einsum("i,ij->j", aux1, self.v)
            + np.einsum("i,ij->j", aux2, self.vnor)
            + np.einsum("i,ij->j", aux3, self.vbin)
        ) * np.pi / vol
        self.r -= cm
        self.v -= lm

    def integrate_angular_momentum(self, dt: float) -> None:
        """Solve J w = L for the deformation's angular velocity, rotate the
        whole midline by the accumulated internal quaternion, and add the
        -w x r counter-rotation to v (main.cpp:11013-11219)."""
        ds, cR, cN, cB, m00, m11, m22 = self._section_integrals()

        def moment2(a, an, ab_, b, bn, bb):
            """sum over section of p_a q_b dV up to O(w^2,h^2) terms, for
            fields p=(a,an,ab_), q=(b,bn,bb) in (center, normal, binormal)
            components."""
            return (
                cR * (a * b * m00 + an * bn * m11 + ab_ * bb * m22)
                + cN * m11 * (a * bn + b * an)
                + cB * m22 * (a * bb + b * ab_)
            )

        r, n, b_ = self.r, self.nor, self.bin
        v, vn, vb = self.v, self.vnor, self.vbin
        X, Y, Z = r[:, 0], r[:, 1], r[:, 2]
        JXY = -np.sum(ds * moment2(X, n[:, 0], b_[:, 0], Y, n[:, 1], b_[:, 1]))
        JZX = -np.sum(ds * moment2(Z, n[:, 2], b_[:, 2], X, n[:, 0], b_[:, 0]))
        JYZ = -np.sum(ds * moment2(Y, n[:, 1], b_[:, 1], Z, n[:, 2], b_[:, 2]))
        XX = ds * moment2(X, n[:, 0], b_[:, 0], X, n[:, 0], b_[:, 0])
        YY = ds * moment2(Y, n[:, 1], b_[:, 1], Y, n[:, 1], b_[:, 1])
        ZZ = ds * moment2(Z, n[:, 2], b_[:, 2], Z, n[:, 2], b_[:, 2])
        JXX = np.sum(YY + ZZ)
        JYY = np.sum(ZZ + XX)
        JZZ = np.sum(YY + XX)  # reference parity (main.cpp:11076)

        # angular momentum of deformation: AM = sum r x v dV.  Each term is
        # symmetric moment2 of one position and one velocity field; this
        # deliberately fixes the reference's dimensionally-inconsistent cN
        # term in x_yd (main.cpp:11078 mixes rY*norX into a velocity moment)
        # -- a typo, not a modeling choice; AM_Z differs accordingly.
        xd_y = moment2(v[:, 0], vn[:, 0], vb[:, 0], Y, n[:, 1], b_[:, 1])
        x_yd = moment2(X, n[:, 0], b_[:, 0], v[:, 1], vn[:, 1], vb[:, 1])
        xd_z = moment2(v[:, 0], vn[:, 0], vb[:, 0], Z, n[:, 2], b_[:, 2])
        x_zd = moment2(X, n[:, 0], b_[:, 0], v[:, 2], vn[:, 2], vb[:, 2])
        yd_z = moment2(v[:, 1], vn[:, 1], vb[:, 1], Z, n[:, 2], b_[:, 2])
        y_zd = moment2(Y, n[:, 1], b_[:, 1], v[:, 2], vn[:, 2], vb[:, 2])
        am = np.array(
            [
                np.sum((y_zd - yd_z) * ds),
                np.sum((xd_z - x_zd) * ds),
                np.sum((x_yd - xd_y) * ds),
            ]
        ) * np.pi

        eps = np.finfo(np.float64).eps
        J = np.array(
            [
                [max(JXX, eps), JXY, JZX],
                [JXY, max(JYY, eps), JYZ],
                [JZX, JYZ, max(JZZ, eps)],
            ]
        ) * np.pi
        self.angvel_internal = np.linalg.solve(J, am)

        # integrate internal quaternion *backwards* (counter-rotation)
        w_int = self.angvel_internal
        q = self.quaternion_internal
        dqdt = 0.5 * np.array(
            [
                -w_int[0] * q[1] - w_int[1] * q[2] - w_int[2] * q[3],
                +w_int[0] * q[0] + w_int[1] * q[3] - w_int[2] * q[2],
                -w_int[0] * q[3] + w_int[1] * q[0] + w_int[2] * q[1],
                +w_int[0] * q[2] - w_int[1] * q[1] + w_int[2] * q[0],
            ]
        )
        q = q - dt * dqdt
        self.quaternion_internal = q / np.linalg.norm(q)
        R = _quat_rot(self.quaternion_internal)

        for pos, vel in ((self.r, self.v), (self.nor, self.vnor),
                         (self.bin, self.vbin)):
            pos[:] = pos @ R.T
            vel[:] = vel @ R.T
            vel += np.cross(np.broadcast_to(w_int, pos.shape), pos) * -1.0


def _quat_rot(q: np.ndarray) -> np.ndarray:
    w, x, y, z = q
    return np.array(
        [
            [1 - 2 * (y * y + z * z), 2 * (x * y - z * w), 2 * (x * z + y * w)],
            [2 * (x * y + z * w), 1 - 2 * (x * x + z * z), 2 * (y * z - x * w)],
            [2 * (x * z - y * w), 2 * (y * z + x * w), 1 - 2 * (x * x + y * y)],
        ]
    )
