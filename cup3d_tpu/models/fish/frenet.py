"""Frenet frame integration: midline curve from curvature + torsion.

Reference: Frenet3D::solve (main.cpp:7618-7731) -- forward-Euler integration
of the Frenet-Serret ODEs along the arc-length grid, carrying both the frame
(tangent ksi, normal, binormal) and its time derivative, renormalizing each
step.  The midline starts at the origin pointing +x with normal +y.

This is a short sequential recurrence over ~10^2 points; it stays host-side
NumPy (a lax.scan would gain nothing at this size and cost a compile).
"""

from __future__ import annotations

import numpy as np


def frenet_solve(rs, curv, curv_dt, tors, tors_dt):
    """Integrate the midline and its velocity from curvature/torsion.

    Args: all (Nm,) float64.
    Returns dict of (Nm,3) arrays: r, v, nor, vnor, bin, vbin.
    """
    nm = len(rs)
    r = np.zeros((nm, 3))
    v = np.zeros((nm, 3))
    nor = np.zeros((nm, 3))
    vnor = np.zeros((nm, 3))
    bin_ = np.zeros((nm, 3))
    vbin = np.zeros((nm, 3))

    ksi = np.array([1.0, 0.0, 0.0])
    vksi = np.zeros(3)
    nor[0] = (0.0, 1.0, 0.0)
    bin_[0] = (0.0, 0.0, 1.0)
    eps = np.finfo(np.float64).eps

    for i in range(1, nm):
        k, dk = curv[i - 1], curv_dt[i - 1]
        tau, dtau = tors[i - 1], tors_dt[i - 1]
        n0, b0, vn0, vb0 = nor[i - 1], bin_[i - 1], vnor[i - 1], vbin[i - 1]
        dksi = k * n0
        dnu = -k * ksi + tau * b0
        dbin = -tau * n0
        dvksi = dk * n0 + k * vn0
        dvnu = -dk * ksi - k * vksi + dtau * b0 + tau * vb0
        dvbin = -dtau * n0 - tau * vn0
        ds = rs[i] - rs[i - 1]
        r[i] = r[i - 1] + ds * ksi
        nor[i] = n0 + ds * dnu
        ksi = ksi + ds * dksi
        bin_[i] = b0 + ds * dbin
        v[i] = v[i - 1] + ds * vksi
        vnor[i] = vn0 + ds * dvnu
        vksi = vksi + ds * dvksi
        vbin[i] = vb0 + ds * dvbin
        for vec in (ksi, nor[i], bin_[i]):
            d = vec @ vec
            if d > eps:
                vec *= 1.0 / np.sqrt(d)

    return {"r": r, "v": v, "nor": nor, "vnor": vnor, "bin": bin_, "vbin": vbin}
