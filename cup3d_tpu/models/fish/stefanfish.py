"""StefanFish: the self-propelled carangiform swimmer.

Reference: StefanFish (main.cpp:8960-8978, 15668-15981) on top of Fish
(main.cpp:7586-7617, 10597-10958).  Combines:

- CurvatureDefinedFishData gait generation + deformation-momentum removal;
- PID feedback on streamwise/lateral position (alpha/beta), depth (gamma)
  and roll (angular-velocity correction) toward the spawn point;
- the RL interface: act() commands bending/period/torsion,
  state() returns the 25-dim observation with 3 shear sensors.

The SDF/udef rasterization runs as one jitted window kernel
(cup3d_tpu.models.fish.rasterize) instead of per-block surface scatters.
"""

from __future__ import annotations

from collections import deque
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.models.base import Obstacle, quat_to_rot
from cup3d_tpu.models.fish.curvature import CurvatureDefinedFishData
from cup3d_tpu.models.fish.rasterize import rasterize_midline, rasterize_points
from cup3d_tpu.models.fish.shapes import compute_widths_heights
from cup3d_tpu.ops.chi import heaviside


@jax.jit
def _raster_scatter_blocks(xc, scat, midline, position, rot):
    """Gather candidate block centers -> midline rasterization -> scatter
    back into full forest arrays, as ONE jitted dispatch.  Padded rows of
    ``scat`` point one past the end: the gather fills far-away centers
    (sdf -> -inf side) and the scatter drops them."""
    centers = jnp.take(xc, scat, axis=0, mode="fill", fill_value=1e6)
    sdf_c, udef_c = rasterize_points(centers, midline, position, rot)
    nb = xc.shape[0]
    sdf = jnp.full((nb,) + xc.shape[1:4], -1.0, xc.dtype)
    sdf = sdf.at[scat].set(sdf_c, mode="drop")
    udef = jnp.zeros(xc.shape[:4] + (3,), xc.dtype)
    udef = udef.at[scat].set(udef_c, mode="drop")
    return sdf, udef


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("grid_shape", "window_shape"))
def _raster_window_dense(pos, rot, midline, half, h, grid_shape,
                         window_shape):
    """Window snap + midline rasterization + dense placement as ONE jitted
    dispatch (the eager tail cost ~10 tunnel round trips per step)."""
    dtype = half.dtype
    idx0 = jnp.clip(
        jnp.floor((pos - half) / h).astype(jnp.int32),
        0,
        jnp.asarray(np.asarray(grid_shape) - np.asarray(window_shape),
                    jnp.int32),
    )
    origin = idx0.astype(dtype) * h
    starts = (idx0[0], idx0[1], idx0[2])
    sdf_w, udef_w = rasterize_midline(
        origin, h, window_shape, midline, pos, rot,
    )
    sdf = jnp.full(grid_shape, -1.0, dtype)
    sdf = jax.lax.dynamic_update_slice(sdf, sdf_w, starts)
    udef = jnp.zeros(tuple(grid_shape) + (3,), dtype)
    udef = jax.lax.dynamic_update_slice(udef, udef_w, starts + (0,))
    return sdf, udef


def _clip_quantities(fmax, dfmax, dt, fcandidate, dfcandidate, f, df):
    """PID anti-windup clipping (main.cpp:15698-15713): limit both the
    correction and its rate.  Returns (f, df)."""
    if abs(dfcandidate) > dfmax:
        df = dfmax if dfcandidate > 0 else -dfmax
        f = f + dt * df
    elif abs(fcandidate) < fmax:
        f, df = fcandidate, dfcandidate
    else:
        f = fmax if fcandidate > 0 else -fmax
        df = 0.0
    return f, df


class StefanFish(Obstacle):
    def __init__(self, sim, spec: Dict[str, str]):
        super().__init__(sim, spec)
        g = lambda k, d: float(spec.get(k, d))
        b = lambda k: spec.get(k, "0").lower() in ("1", "true")
        self.Tperiod = g("T", 1.0)
        self.phaseShift = g("phi", 0.0)
        amp = g("amplitudeFactor", 1.0)
        self.bCorrectPosition = b("CorrectPosition")
        self.bCorrectPositionZ = b("CorrectPositionZ")
        self.bCorrectRoll = b("CorrectRoll")
        height_name = spec.get("heightProfile", "baseline")
        width_name = spec.get("widthProfile", "baseline")
        self.wyp = g("wyp", 1.0)
        self.wzp = g("wzp", 1.0)
        if (self.bCorrectPosition or self.bCorrectPositionZ or self.bCorrectRoll
                ) and abs(self.quaternion[0] - 1) > 1e-6:
            raise ValueError("PID controllers require zero initial angles")

        # midline resolution follows the finest spacing the grid can offer
        # (reference: sim.hmin, main.cpp:15402); layout-generic
        h = sim.grid.hmin
        self.myFish = CurvatureDefinedFishData(
            self.length, self.Tperiod, self.phaseShift, h, amp
        )
        self.myFish.height, self.myFish.width = compute_widths_heights(
            height_name, width_name, self.length, self.myFish.rS
        )
        self.origC = self.position.copy()  # PID target (spawn point)
        self.r_axis: deque = deque()  # roll-axis history for bCorrectRoll

        # dense uniform layout: a static rasterization window (the deformed
        # fish stays within ~0.6 L of its center; margin for the mollified
        # band).  Block layout: candidate blocks are found per call.
        self._is_blocks = not hasattr(sim.grid, "shape")
        if not self._is_blocks:
            nw = int(np.ceil(1.25 * self.length / h)) + 8
            self._window_shape = tuple(min(nw, n) for n in sim.grid.shape)

    # -- geometry pipeline (Fish::create, main.cpp:10952-10958) ------------

    def update_shape(self, t: float, dt: float) -> None:
        self._apply_position_pid(dt)
        self.myFish.compute_midline(t, dt)
        self.myFish.integrate_linear_momentum()
        self.myFish.integrate_angular_momentum(max(dt, 1e-12))
        self._update_sensor_locations()

    def max_body_speed(self, uinf=None) -> float:
        """Rigid bound + the midline's max deformation speed — the fast,
        host-exact part of the fish's material velocity (see
        Obstacle.max_body_speed for why the pipelined dt chain needs
        this fresh)."""
        base = super().max_body_speed(uinf)
        v = np.asarray(self.myFish.v, np.float64)
        return base + float(np.sqrt((v * v).sum(-1).max()))

    def _apply_position_pid(self, dt: float) -> None:
        """alpha/beta/gamma corrections (StefanFish::create,
        main.cpp:15716-15778)."""
        cf = self.myFish
        q = self.quaternion
        s = self.sim
        # pitch: x-component of the head->mid direction in the lab z-row
        Rrow = np.array(
            [2 * (q[1] * q[3] - q[2] * q[0]), 2 * (q[2] * q[3] + q[1] * q[0]),
             1 - 2 * (q[1] * q[1] + q[2] * q[2])]
        )
        nm = cf.Nm
        d = cf.r[0] - cf.r[nm // 2]
        dn = np.linalg.norm(d) + 1e-21
        pitch = np.arcsin(np.clip(Rrow @ (d / dn), -1.0, 1.0))
        roll = np.arctan2(2 * (q[3] * q[2] + q[0] * q[1]),
                          1 - 2 * (q[1] * q[1] + q[2] * q[2]))
        yaw = np.arctan2(2 * (q[3] * q[0] + q[1] * q[2]),
                         -1 + 2 * (q[0] * q[0] + q[1] * q[1]))
        roll_small = abs(roll) < np.pi / 9
        yaw_small = abs(yaw) < np.pi / 9
        dt_eff = max(dt, 1e-12)

        if self.bCorrectPosition:
            cf.alpha = 1.0 + (self.position[0] - self.origC[0]) / self.length
            cf.dalpha = (self.transVel[0] + s.uinf[0]) / self.length
            if not roll_small:
                cf.alpha, cf.dalpha = 1.0, 0.0
            elif cf.alpha < 0.9:
                cf.alpha, cf.dalpha = 0.9, 0.0
            elif cf.alpha > 1.1:
                cf.alpha, cf.dalpha = 1.1, 0.0
            dy = (self.origC[1] - self.absPos[1]) / self.length
            sign_y = 1.0 if dy > 0 else -1.0
            dphi = yaw - 0.0
            bb = self.wyp * sign_y * dy * dphi if roll_small else 0.0
            dbdt = (bb - cf.beta) / dt_eff if s.step > 1 else 0.0
            cf.beta, cf.dbeta = _clip_quantities(
                1.0, 5.0, dt_eff, bb, dbdt, cf.beta, cf.dbeta
            )
        if self.bCorrectPositionZ:
            dphi = pitch - 0.0
            dz = (self.origC[2] - self.absPos[2]) / self.length
            sign_z = 1.0 if dz > 0 else -1.0
            gg = -self.wzp * dphi * dz * sign_z if (roll_small and yaw_small) else 0.0
            dgdt = (gg - cf.gamma) / dt_eff if s.step > 1 else 0.0
            gmax = 0.10 / self.length
            dRdtmax = 0.1 * self.length / cf.Tperiod
            dgdtmax = abs(gmax * gmax * dRdtmax)
            cf.gamma, cf.dgamma = _clip_quantities(
                gmax, dgdtmax, dt_eff, gg, dgdt, cf.gamma, cf.dgamma
            )

    def _midline_device(self):
        """One packed (Nm, 20) host->device transfer per rasterization —
        eight separate uploads cost ~75 ms each through the TPU tunnel —
        sliced back into the rasterizer's dict on device (free)."""
        cf = self.myFish
        dtype = self.sim.dtype
        packed = np.concatenate(
            [cf.r, cf.v, cf.nor, cf.vnor, cf.bin, cf.vbin,
             cf.width[:, None], cf.height[:, None]], axis=1
        )
        dev = jnp.asarray(packed, dtype)
        return {
            "r": dev[:, 0:3], "v": dev[:, 3:6],
            "nor": dev[:, 6:9], "vnor": dev[:, 9:12],
            "bin": dev[:, 12:15], "vbin": dev[:, 15:18],
            "width": dev[:, 18], "height": dev[:, 19],
        }

    def _rasterize_blocks(self, t: float):
        """Block-layout rasterization: candidate blocks by AABB intersection
        (the TPU analogue of prepare_segPerBlock, main.cpp:10672-10717),
        one batched midline-distance evaluation over their cells, scattered
        into the (nb, bs, bs, bs) forest arrays.

        The candidate cell centers are GATHERED from the driver's cached
        device centers (sim._xc) inside one jitted call — rebuilding and
        uploading them on host, plus the eager scatters, cost ~25 ms/fish/
        step over the TPU tunnel."""
        grid = self.sim.grid
        dtype = self.sim.dtype
        bs = grid.bs
        # fish AABB around the body center, padded per block by the
        # mollification band at that block's spacing (the same margin the
        # surface-probe windows use — ops/surface.probe_margin)
        from cup3d_tpu.ops.surface import probe_margin

        half = probe_margin(self.length, grid.h)  # (nb,)
        lo = grid.origin  # (nb, 3)
        hi = grid.origin + (bs * grid.h)[:, None]
        cand = np.all(hi > self.position - half[:, None], axis=1) & np.all(
            lo < self.position + half[:, None], axis=1
        )
        idx = np.where(cand)[0]
        m = len(idx)
        # bucket the candidate count so XLA retraces only on bucket changes
        mpad = max(16, -(-m // 16) * 16)
        idx_pad = np.full(mpad, grid.nb, np.int64)  # OOB rows -> dropped
        idx_pad[:m] = idx
        xc = getattr(self.sim, "_xc", None)
        if xc is None or xc.shape[0] != grid.nb:
            xc = jnp.asarray(grid.cell_centers(dtype))
        # position/rotation from the device rigid chain in pipelined mode
        # (exact current state; the host mirror above only sizes the AABB,
        # whose 8h margin covers the grouped-read staleness of ~8 steps x
        # CFL*h of drift — see ops/surface.probe_margin)
        pos, rot = self.pos_rot_device(dtype)
        return _raster_scatter_blocks(
            xc, jnp.asarray(idx_pad, jnp.int32), self._midline_device(),
            pos, rot,
        )

    def rasterize(self, t: float):
        if self._is_blocks:
            return self._rasterize_blocks(t)
        cf = self.myFish
        grid = self.sim.grid
        h = grid.h
        dtype = self.sim.dtype
        half = 0.5 * np.asarray(self._window_shape) * h
        # rigid state from the device pack in pipelined mode (host mirrors
        # trail one step there), else uploaded mirrors; the window snap is
        # traced either way so both branches share one code path
        pos, rot = self.pos_rot_device(dtype)
        return _raster_window_dense(
            pos, rot, self._midline_device(),
            jnp.asarray(half, dtype), jnp.asarray(h, dtype),
            tuple(grid.shape), tuple(self._window_shape),
        )

    def create(self, t: float) -> None:
        from cup3d_tpu.ops.chi import towers_chi

        sdf, udef = self.rasterize(t)
        self.sdf = sdf
        self.chi = towers_chi(
            self.sim.grid.pad_scalar(sdf, 1), self.sim.grid.h
        )
        # deformation velocity only matters inside the mollified band
        self.udef = udef * (self.chi > 0)[..., None]

    # -- rigid-body override: roll correction ------------------------------

    def supports_device_update(self) -> bool:
        # roll correction mutates angVel on host right after the 6x6 solve
        return super().supports_device_update() and not self.bCorrectRoll

    def compute_velocities(self, moments) -> None:
        super().compute_velocities(moments)
        if not self.bCorrectRoll:
            return
        cf = self.myFish
        s = self.sim
        q = self.quaternion
        o = self.angVel
        dq = 0.5 * np.array(
            [
                -o[0] * q[1] - o[1] * q[2] - o[2] * q[3],
                +o[0] * q[0] + o[1] * q[3] - o[2] * q[2],
                -o[0] * q[3] + o[1] * q[0] + o[2] * q[1],
                +o[0] * q[2] - o[1] * q[1] + o[2] * q[0],
            ]
        )
        nom = 2 * (q[3] * q[2] + q[0] * q[1])
        dnom = 2 * (dq[3] * q[2] + dq[0] * q[1] + q[3] * dq[2] + q[0] * dq[1])
        denom = 1 - 2 * (q[1] * q[1] + q[2] * q[2])
        ddenom = -4 * (q[1] * dq[1] + q[2] * dq[2])
        arg = nom / denom
        darg = (dnom * denom - nom * ddenom) / denom**2
        a = np.arctan2(nom, denom)
        da = darg / (1 + arg * arg)

        # running 5-second average of the head->tail axis = roll axis
        nm = cf.Nm
        d = cf.r[0] - cf.r[nm - 1]
        dn = np.linalg.norm(d) + 1e-21
        self.r_axis.append(np.array([-d[0] / dn, -d[1] / dn, -d[2] / dn, s.dt]))
        roll_axis = np.zeros(3)
        time_roll = 0.0
        keep = 0
        for entry in reversed(self.r_axis):
            if time_roll + entry[3] > 5.0:
                break
            roll_axis += entry[:3] * entry[3]
            time_roll += entry[3]
            keep += 1
        for _ in range(len(self.r_axis) - keep):
            self.r_axis.popleft()
        time_roll += 1e-21
        roll_axis /= time_roll
        if s.time < 1.0 or time_roll < 1.0:
            return
        o -= (o @ roll_axis) * roll_axis  # kill the roll component
        corr, _ = _clip_quantities(0.025, 1e4, s.dt, a + 0.05 * da, 0.0, 0.0, 0.0)
        o -= corr * roll_axis
        self.angVel = o

    # -- sensors / RL interface (main.cpp:15860-15981) ---------------------

    def _update_sensor_locations(self) -> None:
        cf = self.myFish
        rot = quat_to_rot(self.quaternion)
        to_comp = lambda x: self.position + rot @ x
        cf.sensorLocation[0:3] = to_comp(cf.r[0])
        # station with rS[ss] <= 0.04 L < rS[ss+1] (main.cpp:11438)
        ss = int(np.searchsorted(cf.rS, 0.04 * self.length, side="right")) - 1
        ss = min(max(ss, 1), cf.Nm - 2)
        offset = np.pi / 2 if cf.height[ss] > cf.width[ss] else 0.0
        for idx, theta in ((1, offset), (2, offset + np.pi)):
            p = (
                cf.r[ss]
                + cf.width[ss] * np.cos(theta) * cf.nor[ss]
                + cf.height[ss] * np.sin(theta) * cf.bin[ss]
            )
            cf.sensorLocation[3 * idx : 3 * idx + 3] = to_comp(p)

    def act(self, t_rl_action: float, action) -> None:
        action = list(np.atleast_1d(action))
        if len(action) > 1 and self.bForcedInSimFrame[2]:
            action[1] = 0.0
        cf = self.myFish
        cf.oldrCurv = cf.lastCurv
        cf.lastCurv = float(action[0])
        cf.lastTact = float(t_rl_action)
        cf.execute(self.sim.time, t_rl_action, action)

    def get_learn_t_period(self) -> float:
        return self.myFish.next_period

    def get_phase(self, t: float) -> float:
        cf = self.myFish
        arg = (
            2 * np.pi * ((t - cf.time0) / cf.periodPIDval + cf.timeshift)
            + np.pi * cf.phaseShift
        )
        return float(np.mod(arg, 2 * np.pi))

    def state(self) -> np.ndarray:
        """25-dim RL observation (main.cpp:15889-15931)."""
        cf = self.myFish
        Tp, L = cf.Tperiod, self.length
        S = np.zeros(25)
        S[0:3] = self.position
        S[3:7] = self.quaternion
        S[7] = self.get_phase(self.sim.time)
        S[8:11] = self.transVel * Tp / L
        S[11:14] = self.angVel * Tp
        S[14] = cf.lastCurv
        S[15] = cf.oldrCurv
        # reference quirk kept for parity: upper/lower sensors are swapped
        # when sampled (main.cpp:15917-15919)
        locs = cf.sensorLocation
        for i, j in ((0, 0), (1, 2), (2, 1)):
            S[16 + 3 * i : 19 + 3 * i] = self.get_shear(locs[3 * j : 3 * j + 3]) * (
                Tp / L
            )
        return S

    def get_shear(self, pos: np.ndarray) -> np.ndarray:
        """Viscous traction nu (grad u + grad u^T) . n_hat at a point, with
        n_hat the outward body normal from -grad(chi).

        Dense-field equivalent of the reference's nearest-surface-point
        viscous force lookup (getShear, main.cpp:15933-15981).
        """
        s = self.sim
        grid = s.grid
        pos = np.asarray(pos, np.float64)
        if self._is_blocks:
            # holding leaf, finest level first (holdingBlockID,
            # main.cpp:15933-15981); sample the 4^3 patch inside the block,
            # clamped to its interior (sensors sit on the body surface whose
            # blocks are at the finest level, so the clamp is <= 1 cell)
            bs = grid.bs
            slot = -1
            for l in range(grid.tree.cfg.level_max - 1, -1, -1):
                hl = grid.h0 / (1 << l)
                bpos = np.floor(pos / (bs * hl)).astype(int)
                n = grid.tree.blocks_per_dim(l)
                if np.any(bpos < 0) or np.any(bpos >= np.asarray(n)):
                    continue
                sl = grid._slot_maps[l][tuple(bpos)]
                if sl >= 0:
                    slot, h = int(sl), hl
                    bcell0 = bpos * bs
                    break
            if slot < 0:
                return np.zeros(3)
            gidx = np.floor(pos / h - 0.5).astype(int)
            lidx = np.clip(gidx - bcell0, 1, bs - 3)
            idx = bcell0 + lidx
            patch_v = jax.lax.dynamic_slice(
                s.state["vel"][slot], tuple(lidx - 1) + (0,), (4, 4, 4, 3)
            )
            patch_c = jax.lax.dynamic_slice(
                s.state["chi"][slot], tuple(lidx - 1), (4, 4, 4)
            )
        else:
            h = grid.h
            idx = np.clip(
                np.floor(pos / h - 0.5).astype(int), 1,
                np.asarray(grid.shape) - 3,
            )
            patch_v = jax.lax.dynamic_slice(
                s.state["vel"], tuple(idx - 1) + (0,), (4, 4, 4, 3)
            )
            patch_c = jax.lax.dynamic_slice(s.state["chi"], tuple(idx - 1), (4, 4, 4))
        pv = np.asarray(patch_v, np.float64)
        pc = np.asarray(patch_c, np.float64)
        # centered gradients on the 2x2x2 interior of the patch
        gv = np.stack(np.gradient(pv, h, axis=(0, 1, 2)), axis=-1)[1:3, 1:3, 1:3]
        gc = np.stack(np.gradient(pc, h, axis=(0, 1, 2)), axis=-1)[1:3, 1:3, 1:3]
        # trilinear weights of pos within the interior cell corners
        frac = np.asarray(pos) / h - 0.5 - idx
        w = np.ones((2, 2, 2))
        for ax in range(3):
            t = np.clip(frac[ax], 0.0, 1.0)
            shape = [1, 1, 1]
            shape[ax] = 2
            w = w * np.array([1 - t, t]).reshape(shape)
        gv_p = np.einsum("xyz,xyzcd->cd", w, gv)  # d u_c / d x_d
        gc_p = np.einsum("xyz,xyzd->d", w, gc)
        n = -gc_p / (np.linalg.norm(gc_p) + 1e-21)
        return s.nu * (gv_p + gv_p.T) @ n

    def save_midline(self, step_id: int, filename: str = "fish") -> None:
        """writeMidline2File (main.cpp:8116-8146)."""
        cf = self.myFish
        rows = "\n".join(
            f"{cf.rS[i]:g} {cf.r[i,0]:g} {cf.r[i,1]:g} {cf.r[i,2]:g} "
            f"{cf.v[i,0]:g} {cf.v[i,1]:g} {cf.v[i,2]:g}"
            for i in range(cf.Nm)
        )
        self.sim.logger.write(
            f"{filename}_midline_{step_id:07d}.txt", "s x y z vX vY vZ\n" + rows + "\n"
        )
