"""Fish subsystem: midline kinematics (host, NumPy) + SDF rasterization (JAX).

Reference layer L3b (SURVEY.md section 2): FishMidlineData, Schedulers,
Frenet3D, MidlineShapes, CurvatureDefinedFishData, StefanFish,
PutFishOnBlocks (main.cpp:7586-9088, 10597-12198, 15434-15981).

Split of responsibilities (TPU-first, not a port):

- Everything that is a small sequential ODE / spline over the ~10^2-point
  midline stays on host in NumPy (`interpolation`, `schedulers`, `frenet`,
  `shapes`, `midline`, `curvature`).
- The per-cell work -- signed distance of every grid cell to the deforming
  body and the deformation-velocity field -- is one jitted JAX kernel over a
  dense window (`rasterize`), replacing the reference's per-block surface
  point scattering (PutFishOnBlocks, main.cpp:11350-11926) with a
  vectorized distance-to-elliptical-cone-segments formulation.
"""

from cup3d_tpu.models.fish.stefanfish import StefanFish  # noqa: F401
