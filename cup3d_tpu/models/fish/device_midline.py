"""Device-resident fish midline: the pure-jnp twin of the host gait path.

The host pipeline (curvature.py -> frenet.py -> midline.py) re-evaluates the
midline in NumPy every step and re-stages the (Nm, 20) pack through the TPU
tunnel — a constant ~28-43 ms/step of host time (BENCH_r05).  For the scan
megaloop the whole chain must be a pure function of ``(t, dt, carry)``, so
this module freezes the *gait parameters* (scheduler states, PID outputs,
wave phase bookkeeping) once per megaloop build and evaluates the midline as
jnp ops inside the jitted scan body.

Freezability: the scheduler states only mutate through RL actions and PID
controllers.  ``device_midline_eligible`` admits exactly the steady-gait
fish (no TperiodPID, no torsion control, no period transition in flight, no
position/depth/roll PID), for which every frozen parameter is constant over
any future window.  The wave-phase bookkeeping (``time0``/``timeshift``) is
safe to freeze because the host's in-window rewrite
``timeshift += (t - time0)/Tp; time0 = t`` preserves the wave argument
``2 pi ((t - time0)/Tp + timeshift)`` exactly when the period is constant —
so host fallback after a megaloop resumes bit-compatibly.

Every stage is a line-for-line port of the host algorithm (the references
cite the same main.cpp ranges as the host files); equivalence at several
gait phases is asserted by tests/test_megaloop.py.
"""

from __future__ import annotations

import copy

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.models.base import quat_to_rot_dev
from cup3d_tpu.models.fish.interpolation import natural_cubic_spline

# geometric reductions pin HIGHEST matmul precision for the same reason as
# models/fish/rasterize.py: default bf16-grade precision on TPU perturbs the
# midline at the SDF scale of a thin section
_HI = jax.lax.Precision.HIGHEST
# the host renorm / inertia-floor threshold (float64 eps even in f32 runs:
# it is a do-not-divide-by-zero guard, not a solver tolerance)
_EPS64 = float(np.finfo(np.float64).eps)

# gait spline constants (compute_midline, main.cpp:15475-15479)
_CURV_POINTS = np.array([0.0, 0.15, 0.4, 0.65, 0.9, 1.0])
_CURV_VALUES = np.array([0.82014, 1.46515, 2.57136, 3.75425, 5.09147, 5.70449])
_BEND_POINTS = np.array([-0.5, -0.25, 0.0, 0.25, 0.5, 0.75, 1.0])


def device_midline_eligible(ob) -> bool:
    """True when the fish's gait is frozen-parameter representable: every
    scheduler/PID input that could mutate between steps is inactive, so
    ``freeze_gait`` captures the exact kinematics for all future t."""
    cf = getattr(ob, "myFish", None)
    if cf is None:
        return False
    if getattr(ob, "_is_blocks", True):
        return False  # uniform dense window only (the megaloop's layout)
    if cf.TperiodPID or cf.control_torsion:
        return False
    if cf.current_period != cf.next_period:
        return False
    if ob.bCorrectPosition or ob.bCorrectPositionZ or ob.bCorrectRoll:
        return False
    return ob.supports_device_update()


def freeze_gait(ob, t: float, dtype):
    """Snapshot the gait parameters at host time ``t`` into a dict of device
    arrays + python scalars that ``midline_state_device`` consumes.

    Returns None when the scheduler state is not provably constant over
    future steps (e.g. a period transition is mid-flight), in which case
    the caller must stay on the host midline path.
    """
    cf = ob.myFish
    L = float(cf.length)

    # -- period: replicate compute_midline's scheduler interplay on a
    # scratch copy (main.cpp:15467-15474) and demand a constant outcome
    sched = copy.deepcopy(cf.periodScheduler)
    sched.transition_scalar(
        t, cf.transition_start,
        cf.transition_start + cf.transition_duration,
        cf.current_period, cf.next_period,
    )
    if float(np.max(np.abs(sched.dparams_t0))) != 0.0:
        return None
    p0, p1 = float(sched.params_t0[0]), float(sched.params_t1[0])
    if not (p0 == p1 == cf.current_period == cf.next_period):
        return None
    Tp, dTp = sched.get_scalar(t)
    if dTp != 0.0 or Tp <= 0.0:
        return None

    # -- amplitude envelope: the host forces this exact transition every
    # step (compute_midline, main.cpp:15480-15483), so replicating it once
    # captures the scheduler's fixed point
    env = copy.deepcopy(cf.curvatureScheduler)
    curvature_points = _CURV_POINTS * L
    curvature_values = _CURV_VALUES / L
    env.transition_between(0.0, 0.0, cf.Tperiod, np.zeros(6), curvature_values)
    env_p0 = natural_cubic_spline(curvature_points, env.params_t0, cf.rS)
    env_p1 = natural_cubic_spline(curvature_points, env.params_t1, cf.rS)
    env_dp0 = natural_cubic_spline(curvature_points, env.dparams_t0, cf.rS)
    env_t0, env_t1 = float(env.t0), float(env.t1)
    if env_t0 < 0:
        # never-started scheduler returns params_t0 for all t: encode as a
        # saturated past window so the device gate picks env_p1 == env_p0
        env_p1 = env_p0.copy()
        env_dp0 = np.zeros_like(env_p0)
        env_t0, env_t1 = 0.0, -1.0

    # -- pitching cylinder: gamma/dgamma only move under the depth PID
    # (excluded by eligibility), so R/Rdot freeze (main.cpp:15524-15530)
    if abs(cf.gamma) > 1e-10:
        R = 1.0 / cf.gamma
        Rdot = -cf.dgamma / cf.gamma ** 2
    else:
        R = 1e10 if cf.gamma >= 0 else -1e10
        Rdot = 0.0

    arr = lambda a: jnp.asarray(a, dtype)
    return {
        "rs": arr(cf.rS),
        "width": arr(cf.width),
        "height": arr(cf.height),
        "env_p0": arr(env_p0), "env_p1": arr(env_p1), "env_dp0": arr(env_dp0),
        "env_t0": env_t0, "env_t1": env_t1,
        "rb_p": arr(cf.rlBendingScheduler.params_t0),
        "rb_t0": float(cf.rlBendingScheduler.t0),
        "bend": arr(_BEND_POINTS),
        "Tp": float(Tp),
        "time0": float(cf.time0),
        "timeshift": float(cf.timeshift),
        "phase": float(cf.phaseShift),
        "wavelen": float(cf.waveLength),
        "L": L,
        "af": float(cf.amplitudeFactor),
        "alpha": float(cf.alpha), "dalpha": float(cf.dalpha),
        "beta": float(cf.beta), "dbeta": float(cf.dbeta),
        "R": float(R), "Rdot": float(Rdot),
    }


def _hermite_dev(x0, x1, x, y0, y1, dy0, dy1):
    """jnp twin of interpolation.cubic_hermite; returns (y, dy/dx)."""
    xr = x - x0
    dx = x1 - x0
    a = (dy0 + dy1) / (dx * dx) - 2.0 * (y1 - y0) / (dx * dx * dx)
    b = (-2.0 * dy0 - dy1) / dx + 3.0 * (y1 - y0) / (dx * dx)
    y = a * xr ** 3 + b * xr ** 2 + dy0 * xr + y0
    dy = 3.0 * a * xr ** 2 + 2.0 * b * xr + dy0
    return y, dy


def _frenet_scan_dev(rs, curv, dcurv):
    """lax.scan twin of frenet.frenet_solve with zero torsion (torsion
    control is excluded by eligibility): forward-Euler Frenet-Serret
    integration carrying frame + time derivative, renormalizing each step."""
    dtype = rs.dtype
    ds = rs[1:] - rs[:-1]
    z3 = jnp.zeros(3, dtype)
    e_x = jnp.asarray([1.0, 0.0, 0.0], dtype)
    e_y = jnp.asarray([0.0, 1.0, 0.0], dtype)
    e_z = jnp.asarray([0.0, 0.0, 1.0], dtype)

    def renorm(vec):
        d = jnp.dot(vec, vec, precision=_HI)
        return jnp.where(d > _EPS64,
                         vec * jax.lax.rsqrt(jnp.maximum(d, _EPS64)), vec)

    def body(carry, x):
        ksi, vksi, r, v, n0, vn0, b0, vb0 = carry
        k, dk, dsi = x
        dksi = k * n0
        dnu = -k * ksi
        dvksi = dk * n0 + k * vn0
        dvnu = -dk * ksi - k * vksi  # OLD vksi, as the host loop
        r_i = r + dsi * ksi          # OLD ksi
        nor_i = renorm(n0 + dsi * dnu)
        ksi_n = renorm(ksi + dsi * dksi)
        bin_i = renorm(b0)           # torsion = 0: dbin = 0
        v_i = v + dsi * vksi         # OLD vksi
        vnor_i = vn0 + dsi * dvnu
        vksi_n = vksi + dsi * dvksi
        vbin_i = vb0                 # dvbin = 0
        new = (ksi_n, vksi_n, r_i, v_i, nor_i, vnor_i, bin_i, vbin_i)
        return new, (r_i, v_i, nor_i, vnor_i, bin_i, vbin_i)

    init = (e_x, z3, z3, z3, e_y, z3, e_z, z3)
    _, ys = jax.lax.scan(body, init, (curv[:-1], dcurv[:-1], ds))
    row0 = (z3, z3, e_y, z3, e_z, z3)
    out = tuple(jnp.concatenate([first[None], rest], axis=0)
                for first, rest in zip(row0, ys))
    return dict(zip(("r", "v", "nor", "vnor", "bin", "vbin"), out))


def _pitching_dev(r, v, R, Rdot):
    """jnp twin of perform_pitching_motion (main.cpp:15521-15571)."""
    x0N, y0N = r[-1, 0], r[-1, 1]
    x0Nd, y0Nd = v[-1, 0], v[-1, 1]
    phi = jnp.arctan2(y0N, x0N)
    phidot = (y0Nd / x0N - y0N * x0Nd / x0N ** 2) / (1.0 + (y0N / x0N) ** 2)
    M = jnp.hypot(x0N, y0N)
    Mdot = (x0N * x0Nd + y0N * y0Nd) / M
    cphi, sphi = jnp.cos(phi), jnp.sin(phi)
    x0, y0 = r[:, 0], r[:, 1]
    x0d, y0d = v[:, 0], v[:, 1]
    x1 = cphi * x0 - sphi * y0
    y1 = sphi * x0 + cphi * y0
    x1d = cphi * x0d - sphi * y0d + (-sphi * x0 - cphi * y0) * phidot
    y1d = sphi * x0d + cphi * y0d + (cphi * x0 - sphi * y0) * phidot
    theta = (M - x1) / R
    cth, sth = jnp.cos(theta), jnp.sin(theta)
    thetad = (Mdot - x1d) / R - (M - x1) / R ** 2 * Rdot
    r_new = jnp.stack([M - R * sth, y1, R - R * cth], axis=1)
    v_new = jnp.stack(
        [Mdot - Rdot * sth - R * cth * thetad, y1d,
         Rdot - Rdot * cth + R * sth * thetad], axis=1)
    return r_new, v_new


def _recompute_normals_dev(rs, r, v, nor, vnor):
    """jnp twin of recompute_normal_vectors (main.cpp:15572-15667)."""
    hp = (rs[2:] - rs[1:-1])[:, None]
    hm = (rs[1:-1] - rs[:-2])[:, None]
    frac = hp / hm
    am = -frac * frac
    a = frac * frac - 1.0
    denom = 1.0 / (hp * (1.0 + frac))
    t_mid = (am * r[:-2] + a * r[1:-1] + r[2:]) * denom
    dt_mid = (am * v[:-2] + a * v[1:-1] + v[2:]) * denom
    ids0 = 1.0 / (rs[1] - rs[0])
    idsN = 1.0 / (rs[-2] - rs[-1])
    t_vec = jnp.concatenate(
        [((r[1] - r[0]) * ids0)[None], t_mid, ((r[-2] - r[-1]) * idsN)[None]])
    dt_vec = jnp.concatenate(
        [((v[1] - v[0]) * ids0)[None], dt_mid, ((v[-2] - v[-1]) * idsN)[None]])
    dot = jnp.sum(nor * t_vec, axis=1, keepdims=True)
    ddot = (jnp.sum(vnor * t_vec, axis=1)
            + jnp.sum(nor * dt_vec, axis=1))[:, None]
    nor_new = nor - dot * t_vec
    nor_out = nor_new / jnp.linalg.norm(nor_new, axis=1, keepdims=True)
    vnor_out = vnor - ddot * t_vec - dot * dt_vec
    bin_new = jnp.cross(t_vec, nor_out)
    bin_out = bin_new / jnp.linalg.norm(bin_new, axis=1, keepdims=True)
    vbin_out = jnp.cross(dt_vec, nor_out) + jnp.cross(t_vec, vnor_out)
    return nor_out, vnor_out, bin_out, vbin_out


def _d_ds_dev(rs, vals):
    """jnp twin of midline._d_ds (one-sided ends, averaged interior)."""
    ds = rs[1:] - rs[:-1]
    if vals.ndim == 2:
        ds = ds[:, None]
    fwd = (vals[1:] - vals[:-1]) / ds
    return jnp.concatenate([fwd[:1], 0.5 * (fwd[1:] + fwd[:-1]), fwd[-1:]],
                           axis=0)


def _section_integrals_dev(rs, r, nor, bin_, width, height):
    """jnp twin of FishMidlineData._section_integrals."""
    ds = jnp.concatenate([
        (0.5 * (rs[1] - rs[0]))[None],
        0.5 * (rs[2:] - rs[:-2]),
        (0.5 * (rs[-1] - rs[-2]))[None],
    ])
    c = jnp.cross(nor, bin_)
    cR = jnp.sum(c * _d_ds_dev(rs, r), axis=1)
    cN = jnp.sum(c * _d_ds_dev(rs, nor), axis=1)
    cB = jnp.sum(c * _d_ds_dev(rs, bin_), axis=1)
    m00 = width * height
    m11 = 0.25 * width ** 3 * height
    m22 = 0.25 * width * height ** 3
    return ds, cR, cN, cB, m00, m11, m22


def _remove_linear_momentum_dev(si, r, v, nor, vnor, bin_, vbin):
    """jnp twin of integrate_linear_momentum (main.cpp:10961-11012)."""
    ds, cR, cN, cB, m00, m11, m22 = si
    aux1 = m00 * cR * ds
    aux2 = m11 * cN * ds
    aux3 = m22 * cB * ds
    vol = jnp.sum(aux1) * jnp.pi
    dot = lambda w, x: jnp.einsum("i,ij->j", w, x, precision=_HI)
    cm = (dot(aux1, r) + dot(aux2, nor) + dot(aux3, bin_)) * jnp.pi / vol
    lm = (dot(aux1, v) + dot(aux2, vnor) + dot(aux3, vbin)) * jnp.pi / vol
    return r - cm, v - lm


def _remove_angular_momentum_dev(si, dt, qint, r, v, nor, vnor, bin_, vbin):
    """jnp twin of integrate_angular_momentum (main.cpp:11013-11219):
    J w = L solve, backwards internal-quaternion step, counter-rotation.
    Returns (r, v, nor, vnor, bin, vbin, qint_new)."""
    ds, cR, cN, cB, m00, m11, m22 = si

    def moment2(a, an, ab_, b, bn, bb):
        return (cR * (a * b * m00 + an * bn * m11 + ab_ * bb * m22)
                + cN * m11 * (a * bn + b * an)
                + cB * m22 * (a * bb + b * ab_))

    n, b_ = nor, bin_
    X, Y, Z = r[:, 0], r[:, 1], r[:, 2]
    JXY = -jnp.sum(ds * moment2(X, n[:, 0], b_[:, 0], Y, n[:, 1], b_[:, 1]))
    JZX = -jnp.sum(ds * moment2(Z, n[:, 2], b_[:, 2], X, n[:, 0], b_[:, 0]))
    JYZ = -jnp.sum(ds * moment2(Y, n[:, 1], b_[:, 1], Z, n[:, 2], b_[:, 2]))
    XX = ds * moment2(X, n[:, 0], b_[:, 0], X, n[:, 0], b_[:, 0])
    YY = ds * moment2(Y, n[:, 1], b_[:, 1], Y, n[:, 1], b_[:, 1])
    ZZ = ds * moment2(Z, n[:, 2], b_[:, 2], Z, n[:, 2], b_[:, 2])
    JXX = jnp.sum(YY + ZZ)
    JYY = jnp.sum(ZZ + XX)
    JZZ = jnp.sum(YY + XX)  # reference parity (main.cpp:11076)

    xd_y = moment2(v[:, 0], vnor[:, 0], vbin[:, 0], Y, n[:, 1], b_[:, 1])
    x_yd = moment2(X, n[:, 0], b_[:, 0], v[:, 1], vnor[:, 1], vbin[:, 1])
    xd_z = moment2(v[:, 0], vnor[:, 0], vbin[:, 0], Z, n[:, 2], b_[:, 2])
    x_zd = moment2(X, n[:, 0], b_[:, 0], v[:, 2], vnor[:, 2], vbin[:, 2])
    yd_z = moment2(v[:, 1], vnor[:, 1], vbin[:, 1], Z, n[:, 2], b_[:, 2])
    y_zd = moment2(Y, n[:, 1], b_[:, 1], v[:, 2], vnor[:, 2], vbin[:, 2])
    am = jnp.stack([
        jnp.sum((y_zd - yd_z) * ds),
        jnp.sum((xd_z - x_zd) * ds),
        jnp.sum((x_yd - xd_y) * ds),
    ]) * jnp.pi

    eps = jnp.asarray(_EPS64, r.dtype)
    J = jnp.stack([
        jnp.stack([jnp.maximum(JXX, eps), JXY, JZX]),
        jnp.stack([JXY, jnp.maximum(JYY, eps), JYZ]),
        jnp.stack([JZX, JYZ, jnp.maximum(JZZ, eps)]),
    ]) * jnp.pi
    w = jnp.linalg.solve(J, am)

    q = qint
    dqdt = 0.5 * jnp.stack([
        -w[0] * q[1] - w[1] * q[2] - w[2] * q[3],
        +w[0] * q[0] + w[1] * q[3] - w[2] * q[2],
        -w[0] * q[3] + w[1] * q[0] + w[2] * q[1],
        +w[0] * q[2] - w[1] * q[1] + w[2] * q[0],
    ])
    q = q - dt * dqdt  # backwards: counter-rotation
    q = q / jnp.linalg.norm(q)
    R = quat_to_rot_dev(q)

    def rot(pos, vel):
        pos_r = jnp.einsum("ij,kj->ik", pos, R, precision=_HI)
        vel_r = jnp.einsum("ij,kj->ik", vel, R, precision=_HI)
        # -w x r counter-rotation, with the ROTATED positions (host order)
        vel_r = vel_r - jnp.cross(jnp.broadcast_to(w, pos_r.shape), pos_r)
        return pos_r, vel_r

    r, v = rot(r, v)
    nor, vnor = rot(nor, vnor)
    bin_, vbin = rot(bin_, vbin)
    return r, v, nor, vnor, bin_, vbin, q


def midline_state_device(gait, t, dt, qint):
    """Evaluate the full midline state at traced time ``t``: gait wave ->
    Frenet integration -> pitching wrap -> normal re-orthonormalization ->
    deformation-momentum removal.  ``qint`` is the carried internal
    quaternion (4,).  Returns (midline dict for rasterize_midline,
    updated qint)."""
    rs = gait["rs"]
    t = jnp.asarray(t, rs.dtype)
    L, Tp = gait["L"], gait["Tp"]

    # amplitude envelope (VectorScheduler.get_fine on frozen fine arrays)
    y, dy = _hermite_dev(gait["env_t0"], gait["env_t1"], t,
                         gait["env_p0"], gait["env_p1"], gait["env_dp0"], 0.0)
    rC = jnp.where(t > gait["env_t1"], gait["env_p1"],
                   jnp.where(t < gait["env_t0"], gait["env_p0"], y))
    inside = (t >= gait["env_t0"]) & (t <= gait["env_t1"])
    vC = jnp.where(inside, dy, jnp.zeros_like(dy))

    # RL bending riding the wave (LearnWaveScheduler.get_fine, frozen
    # history): values at wave coordinate c = s/L - (t - t0)/Twave
    bp, pb = gait["bend"], gait["rb_p"]
    c = rs / L - (t - gait["rb_t0"]) / Tp
    below = c < bp[0]
    above = c > bp[-1]
    j = jnp.clip(jnp.searchsorted(bp, c, side="left"), 1, bp.shape[0] - 1)
    yb, dyb = _hermite_dev(bp[j - 1], bp[j], c, pb[j - 1], pb[j], 0.0, 0.0)
    rB = jnp.where(below, pb[0], jnp.where(above, pb[-1], yb))
    vB = jnp.where(below | above, jnp.zeros_like(dyb), -dyb / Tp)

    # traveling wave (compute_midline, main.cpp:15484-15519)
    darg = 2.0 * jnp.pi / Tp
    arg0 = (2.0 * jnp.pi * ((t - gait["time0"]) / Tp + gait["timeshift"])
            + jnp.pi * gait["phase"])
    arg = arg0 - 2.0 * jnp.pi * rs / (L * gait["wavelen"])
    curv = jnp.sin(arg) + rB + gait["beta"]
    dcurv = jnp.cos(arg) * darg + vB + gait["dbeta"]
    af = gait["af"]
    rK = gait["alpha"] * af * rC * curv
    vK = (gait["alpha"] * af * (vC * curv + rC * dcurv)
          + gait["dalpha"] * af * rC * curv)
    # NOTE: no host-style finite check here — a NaN propagates to the
    # carried umax and the megaloop consumer raises the recoverable
    # nan-velocity failure (sim/megaloop.py)

    sol = _frenet_scan_dev(rs, rK, vK)
    r, v = _pitching_dev(sol["r"], sol["v"], gait["R"], gait["Rdot"])
    nor, vnor, bin_, vbin = _recompute_normals_dev(rs, r, v,
                                                   sol["nor"], sol["vnor"])
    si = _section_integrals_dev(rs, r, nor, bin_, gait["width"],
                                gait["height"])
    r, v = _remove_linear_momentum_dev(si, r, v, nor, vnor, bin_, vbin)
    # the host recomputes the section integrals after the linear shift
    # (each integrate_* calls _section_integrals): replicate for bit parity
    si = _section_integrals_dev(rs, r, nor, bin_, gait["width"],
                                gait["height"])
    dt_eff = jnp.maximum(jnp.asarray(dt, rs.dtype), 1e-12)
    r, v, nor, vnor, bin_, vbin, qint_new = _remove_angular_momentum_dev(
        si, dt_eff, qint, r, v, nor, vnor, bin_, vbin)

    mid = {"r": r, "v": v, "nor": nor, "vnor": vnor, "bin": bin_,
           "vbin": vbin, "width": gait["width"], "height": gait["height"]}
    return mid, qint_new
