"""Obstacle base: 6-DOF rigid-body state + dense-field rasterization contract.

Reference: ``Obstacle`` (main.cpp:7482-7583, 12812-13233) keeps per-block
``ObstacleBlock`` storage (chi, udef, SDF, surface point lists).  The TPU
design replaces the ragged per-block storage with dense per-obstacle device
fields (chi_i, udef_i) produced by a jittable rasterizer, so penalization,
momentum integrals, and force reductions are fused whole-domain kernels.

6-DOF update: the reference integrates translation/rotation with a BDF-like
2nd-order update and GSL LU for the 6x6 momentum system
(computeVelocities, main.cpp:12921-13029; update, main.cpp:13116-13204).
Here the 6x6 solve is numpy (host, tiny) and the quaternion update uses the
exact exponential map.

Device fast path: on the tunneled TPU every blocking host read costs ~75 ms,
so ``rigid_update_device`` runs the same moments -> 6x6 -> position/quaternion
update entirely on device (the 6x6 is block-diagonal about the CM: u = P/m,
omega = J^-1 L).  The driver then fetches one packed QoI vector per step
(``RIGID_PACK`` below) instead of three separate round trips; host mirrors are
refreshed from that single read before any host code consumes them, so the
numerics match the host path to solver-dtype round-trip (asserted by
tests/test_sphere.py::test_device_fast_path_matches_host).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from cup3d_tpu.grid.uniform import UniformGrid
from cup3d_tpu.ops.chi import grad_chi, heaviside
from cup3d_tpu.ops.diagnostics import swim_split


def quat_to_rot(q: np.ndarray) -> np.ndarray:
    """Unit quaternion (w,x,y,z) -> 3x3 rotation matrix."""
    w, x, y, z = q
    return np.array(
        [
            [1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y)],
            [2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x)],
            [2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)],
        ]
    )


def quat_multiply(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    aw, ax, ay, az = a
    bw, bx, by, bz = b
    return np.array(
        [
            aw * bw - ax * bx - ay * by - az * bz,
            aw * bx + ax * bw + ay * bz - az * by,
            aw * by - ax * bz + ay * bw + az * bx,
            aw * bz + ax * by - ay * bx + az * bw,
        ]
    )


def quat_integrate(q: np.ndarray, omega: np.ndarray, dt: float) -> np.ndarray:
    """Exact exponential-map quaternion step for constant omega over dt."""
    th = np.linalg.norm(omega) * dt
    if th < 1e-14:
        return q
    axis = omega / np.linalg.norm(omega)
    dq = np.concatenate([[np.cos(th / 2)], np.sin(th / 2) * axis])
    q = quat_multiply(dq, q)
    return q / np.linalg.norm(q)


# -- device twins of the rigid-body update (single-sync fast path) -----------

RIGID_STATE = 19  # trans(3) ang(3) pos(3) absPos(3) cm(3) quat(4)
RIGID_PACK = 29   # RIGID_STATE + mass(1) + J(9)


def quat_multiply_dev(a, b):
    aw, ax, ay, az = a[0], a[1], a[2], a[3]
    bw, bx, by, bz = b[0], b[1], b[2], b[3]
    return jnp.stack(
        [
            aw * bw - ax * bx - ay * by - az * bz,
            aw * bx + ax * bw + ay * bz - az * by,
            aw * by - ax * bz + ay * bw + az * bx,
            aw * bz + ax * by - ay * bx + az * bw,
        ]
    )


def quat_to_rot_dev(q):
    """Device twin of quat_to_rot."""
    w, x, y, z = q[0], q[1], q[2], q[3]
    return jnp.stack(
        [
            jnp.stack([1 - 2 * (y * y + z * z), 2 * (x * y - w * z),
                       2 * (x * z + w * y)]),
            jnp.stack([2 * (x * y + w * z), 1 - 2 * (x * x + z * z),
                       2 * (y * z - w * x)]),
            jnp.stack([2 * (x * z - w * y), 2 * (y * z + w * x),
                       1 - 2 * (x * x + y * y)]),
        ]
    )


def quat_integrate_dev(q, omega, dt):
    """Device twin of quat_integrate (exact exponential map)."""
    n = jnp.linalg.norm(omega)
    th = n * dt
    axis = omega / jnp.where(n > 0, n, 1.0)
    dq = jnp.concatenate([jnp.cos(th / 2)[None], jnp.sin(th / 2) * axis])
    qn = quat_multiply_dev(dq, q)
    qn = qn / jnp.linalg.norm(qn)
    return jnp.where(th < 1e-14, q, qn)


def rigid_update_device(mom, state, forced_mask, block_mask, uinf, dt):
    """Moments (19,) + rigid state (RIGID_STATE,) -> updated (RIGID_PACK,).

    Device twin of compute_velocities + update: the 6x6 momentum system is
    block-diagonal about the measured CM (reference computeVelocities,
    main.cpp:12921-13029), so u = P/m and omega = J^-1 L; forced/blocked
    components keep their previous values; position/quaternion advance as in
    update (main.cpp:13116-13204)."""
    m = mom[0]
    center, P, L = mom[1:4], mom[4:7], mom[7:10]
    J = mom[10:19].reshape(3, 3)
    has = m > 0
    minv = 1.0 / jnp.where(has, m, 1.0)
    ut0, om0 = state[0:3], state[3:6]
    cm_meas = jnp.where(has, center * minv, state[12:15])
    Jsafe = jnp.where(has, J, jnp.eye(3, dtype=mom.dtype))
    ut = jnp.where(has, P * minv, ut0)
    om = jnp.where(has, jnp.linalg.solve(Jsafe, L), om0)
    ut = jnp.where(forced_mask, ut0, ut)
    om = jnp.where(block_mask, om0, om)
    pos = state[6:9] + dt * (ut + uinf)
    absp = state[9:12] + dt * ut
    cm = cm_meas + dt * (ut + uinf)
    q = quat_integrate_dev(state[15:19], om, dt)
    return jnp.concatenate(
        [ut, om, pos, absp, cm, q, m[None], J.reshape(9)]
    )


def vel_unit_dev(v):
    n = jnp.linalg.norm(v)
    return jnp.where(n > 1e-21, v / jnp.where(n > 0, n, 1.0), 0.0)


class Obstacle:
    """One immersed body.  Subclasses implement ``rasterize()`` (and
    optionally ``update_shape()`` for deforming bodies)."""

    def __init__(self, sim, spec: Dict[str, str]):
        self.sim = sim
        self.spec = spec
        g = lambda k, d: float(spec.get(k, d))
        self.length = g("L", 0.1)
        self.position = np.array(
            [g("xpos", 0.5 * sim.grid.extent[0]),
             g("ypos", 0.5 * sim.grid.extent[1]),
             g("zpos", 0.5 * sim.grid.extent[2])]
        )
        self.quaternion = np.array(
            [g("quat0", 1.0), g("quat1", 0.0), g("quat2", 0.0), g("quat3", 0.0)]
        )
        # planar (yaw) spawn angle in degrees about +z (reference parses
        # planarAngle alongside the explicit quaternion, main.cpp:12820-12837)
        ang = np.deg2rad(g("planarAngle", 0.0))
        if ang != 0.0 and np.allclose(self.quaternion, [1.0, 0.0, 0.0, 0.0]):
            self.quaternion = np.array([np.cos(ang / 2), 0.0, 0.0, np.sin(ang / 2)])
        self.transVel = np.array([g("xvel", 0.0), g("yvel", 0.0), g("zvel", 0.0)])
        self.angVel = np.zeros(3)
        # forced-motion flags (main.cpp:12838-12870)
        forced = spec.get("bForcedInSimFrame", "0") == "1"
        self.bForcedInSimFrame = np.array([forced] * 3)
        self.bBlockRotation = np.array(
            [spec.get("bBlockRotation", "1" if forced else "0") == "1"] * 3
        )
        self.bFixFrameOfRef = spec.get("bFixFrameOfRef", "0") == "1"
        # absolute position: not advected by the moving frame's uinf
        # (reference absPos, main.cpp:13138-13143)
        self.absPos = self.position.copy()

        # filled by create()/integrals
        self.chi: Optional[jnp.ndarray] = None
        self.udef: Optional[jnp.ndarray] = None
        self.mass = 0.0
        self.J = np.zeros((3, 3))
        self.centerOfMass = self.position.copy()
        # force QoI (reference ComputeForces reduction, main.cpp:13079-13115)
        self.force = np.zeros(3)
        self.torque = np.zeros(3)
        self.pres_force = np.zeros(3)
        self.visc_force = np.zeros(3)
        self.pow_out = 0.0
        self.pout_bnd = 0.0
        self.thrust = 0.0
        self.drag = 0.0
        self.def_power = 0.0
        self.def_power_bnd = 0.0
        self.p_locom = 0.0
        self.Pthrust = 0.0
        self.Pdrag = 0.0
        self.EffPDef = 0.0
        self.EffPDefBnd = 0.0
        # collision latch (reference collision_counter/u_collision,
        # main.cpp:7546-7552, 13069-13077)
        self.collision_counter = 0.0
        self.collision_vel = np.zeros(3)
        self.collision_angvel = np.zeros(3)
        # device fast path (rigid_update_device): set by UpdateObstacles for
        # the current step, consumed by body_velocity_field/ComputeForces;
        # host mirrors are refreshed from the packed per-step read
        self._dev_rigid: Optional[dict] = None

    # -- checkpointing -----------------------------------------------------

    def __getstate__(self):
        """Pickle the kinematic/dynamic state only: the sim backref and all
        device arrays (chi/udef/caches) are rebuilt by create_obstacles()
        after restore (io/checkpoint.py)."""
        state = {}
        for k, v in self.__dict__.items():
            if k in ("sim", "_dev_rigid") or isinstance(v, jax.Array):
                continue
            if k.endswith("_cache"):
                continue
            state[k] = v
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.sim = None
        self.chi = None
        self.udef = None
        self._dev_rigid = None

    # -- geometry ---------------------------------------------------------

    def rasterize(self, t: float):
        """Return (sdf, udef) dense fields; sdf > 0 inside, udef (.,3)."""
        raise NotImplementedError

    def max_body_speed(self, uinf=None) -> float:
        """Fresh host-side bound on this body's maximum material speed in
        the sim frame: rigid translation (+ frame velocity) + rotation at
        the body radius (+ deformation; fish override).  The pipelined dt
        chain floors its CFL scale with this: the packed fluid max|u| can
        lag ~(1+max_inflight)*read_every steps, but the body kinematics
        that DRIVE the acceleration are known on host exactly — measured
        at 256^3, a gait spin-up outruns the stale mirror while dt sits
        at the diffusive cap and the run blows through CFL (the reference
        never faces this: findMaxU re-measures every step,
        main.cpp:8603-8623)."""
        tv = np.asarray(self.transVel, np.float64)
        if uinf is not None:
            tv = tv + np.asarray(uinf, np.float64)
        om = float(np.linalg.norm(np.asarray(self.angVel, np.float64)))
        return float(np.linalg.norm(tv)) + om * 0.5 * float(self.length)

    def update_shape(self, t: float, dt: float) -> None:
        """Advance internal deformation kinematics (fish midline etc.)."""

    def create(self, t: float) -> None:
        """SDF -> chi + udef (reference Obstacle::create + chi kernel).
        The SDF is kept: the surface-point force probe (ops/surface.py)
        takes its outward normals from grad(phi) like the reference."""
        from cup3d_tpu.ops.chi import towers_chi

        sdf, udef = self.rasterize(t)
        self.sdf = sdf
        self.chi = towers_chi(
            self.sim.grid.pad_scalar(sdf, 1), self.sim.grid.h
        )
        self.udef = udef if udef is not None else jnp.zeros(
            self.sim.grid.shape + (3,), self.sim.dtype
        )

    # -- device fast path --------------------------------------------------

    def supports_device_update(self) -> bool:
        """True when the rigid update has no host-only branch this step
        (collision latch active -> host path; subclasses add their own
        vetoes, e.g. StefanFish roll correction)."""
        return self.collision_counter <= 0

    def rigid_state_vec(self) -> np.ndarray:
        """Host mirrors -> (RIGID_STATE,) input for rigid_update_device."""
        return np.concatenate(
            [self.transVel, self.angVel, self.position, self.absPos,
             self.centerOfMass, self.quaternion]
        )

    def rigid_state_dev(self, dtype) -> jnp.ndarray:
        """(RIGID_STATE,) device input for rigid_update_device: chains from
        the previous step's device output when it exists (pipelined mode
        keeps the rigid trajectory device-resident), else uploads the host
        mirrors."""
        d = self._dev_rigid
        if d is not None:
            return d["pack"][:RIGID_STATE]
        return jnp.asarray(self.rigid_state_vec(), dtype)

    def forced_mask_dev(self) -> jnp.ndarray:
        """Cached device mirror of ``bForcedInSimFrame``.  The flags are
        fixed at construction (factory kwargs), so the upload happens
        once; identity-keyed like SimulationData.uinf_device so an
        exotic reassignment still invalidates (the PR 2 mirror
        pattern).  ``*_cache`` attrs are pickle-excluded and rebuild
        after restore."""
        if getattr(self, "_forced_src_cache", None) is not self.bForcedInSimFrame:
            from cup3d_tpu.analysis.runtime import sanctioned_transfer

            with sanctioned_transfer("scalar-upload"):
                self._forced_dev_cache = jnp.asarray(self.bForcedInSimFrame)
            self._forced_src_cache = self.bForcedInSimFrame
        return self._forced_dev_cache

    def block_mask_dev(self) -> jnp.ndarray:
        """Cached device mirror of ``bBlockRotation`` (see
        :meth:`forced_mask_dev`)."""
        if getattr(self, "_block_src_cache", None) is not self.bBlockRotation:
            from cup3d_tpu.analysis.runtime import sanctioned_transfer

            with sanctioned_transfer("scalar-upload"):
                self._block_dev_cache = jnp.asarray(self.bBlockRotation)
            self._block_src_cache = self.bBlockRotation
        return self._block_dev_cache

    def pos_rot_device(self, dtype):
        """(position, rotation-matrix) as device arrays for rasterization:
        from the device rigid pack when pipelined chaining is active (the
        host mirror trails one step there), else uploaded host mirrors."""
        d = self._dev_rigid
        if self.sim.cfg.pipelined and d is not None:
            pack = d["pack"]
            return pack[6:9], quat_to_rot_dev(pack[15:19])
        return (jnp.asarray(self.position, dtype),
                jnp.asarray(quat_to_rot(self.quaternion), dtype))

    def apply_rigid_pack(self, row: np.ndarray, clear_dev: bool = True) -> None:
        """(RIGID_PACK,) output of rigid_update_device -> host mirrors."""
        row = np.asarray(row, np.float64)
        self.transVel = row[0:3]
        self.angVel = row[3:6]
        self.position = row[6:9]
        self.absPos = row[9:12]
        self.centerOfMass = row[12:15]
        self.quaternion = row[15:19]
        if row[19] > 0:
            self.mass = float(row[19])
            self.J = row[20:29].reshape(3, 3)
        if clear_dev:
            self._dev_rigid = None

    # -- rigid-body dynamics ----------------------------------------------

    def body_velocity_field(self) -> jnp.ndarray:
        """u_body = u_trans + omega x r + u_def on the whole grid.

        Uses the driver's device-cached cell centers + jitted kernel and
        memoizes per (step, rigid state): penalization and the force pass
        consume the same field each step."""
        s = self.sim
        dev = self._dev_rigid
        if dev is not None and dev["step"] == s.step:
            # device fast path: rigid state from this step's on-device update
            tag = (s.step, "dev")
            cm, ut, om = dev["cm"], dev["trans"], dev["ang"]
        else:
            tag = (s.step, tuple(self.transVel), tuple(self.angVel),
                   tuple(self.centerOfMass))
            dtype = s.dtype
            cm = jnp.asarray(self.centerOfMass, dtype)
            ut = jnp.asarray(self.transVel, dtype)
            om = jnp.asarray(self.angVel, dtype)
        cached = getattr(self, "_ubody_cache", None)
        if cached is not None and cached[0] == tag:
            return cached[1]
        fn = getattr(s, "_ubody_fn", None)
        if fn is not None:
            field = fn(self.udef, cm, ut, om)
        else:
            x = s.grid.cell_centers(s.dtype)
            r = x - cm
            field = ut + jnp.cross(jnp.broadcast_to(om, r.shape), r) + self.udef
        self._ubody_cache = (tag, field)
        return field

    def compute_velocities(self, moments: Dict[str, np.ndarray]) -> None:
        """Solve the coupled 6x6 momentum system for (u_trans, omega)
        (reference computeVelocities, main.cpp:12921-13029), then override
        forced components."""
        m = moments["mass"]
        if m <= 0:
            return
        cm = moments["center"] / m
        self.centerOfMass = cm
        P = moments["lin_mom"]
        L = moments["ang_mom"]  # about cm
        J = moments["inertia"]  # about cm
        # [[m I, 0], [0, J]] is exact when moments are taken about the CM
        A = np.zeros((6, 6))
        A[:3, :3] = m * np.eye(3)
        A[3:, 3:] = J
        b = np.concatenate([P, L])
        sol = np.linalg.solve(A, b)
        self.mass = m
        self.J = J
        new_ut, new_om = sol[:3], sol[3:]
        self.transVel = np.where(self.bForcedInSimFrame, self.transVel, new_ut)
        self.angVel = np.where(self.bBlockRotation, self.angVel, new_om)
        # a fresh collision overrides the fluid-coupled solve for one step
        # (reference main.cpp:13069-13077)
        if self.collision_counter > 0:
            self.collision_counter -= self.sim.dt
            self.transVel = self.collision_vel.copy()
            self.angVel = self.collision_angvel.copy()

    def update(self, dt: float) -> None:
        """Advance position/orientation (reference update, main.cpp:13116-13204)."""
        uinf = self.sim.uinf
        self.position = self.position + dt * (self.transVel + uinf)
        self.absPos = self.absPos + dt * self.transVel
        self.centerOfMass = self.centerOfMass + dt * (self.transVel + uinf)
        self.quaternion = quat_integrate(self.quaternion, self.angVel, dt)


# QoI packing: the tunneled TPU pays ~75 ms per host read, so per-step
# reductions travel as ONE packed vector instead of one array per quantity
# (the reference's analogue is batching 29 QoI into one MPI_Allreduce,
# main.cpp:13783)

_MOMENT_KEYS = ("mass", "center", "lin_mom", "ang_mom", "inertia")
_FORCE_KEYS = ("pres_force", "visc_force", "torque", "power", "pout_bnd",
               "thrust", "drag", "def_power", "def_power_bnd", "p_locom",
               "n_surf")
# packed force-vector width (3+3+3 vectors + 7 scalars + n_surf): the full
# 19-QoI reduction set of the reference's ComputeForces
# (main.cpp:13089-13108 — surfForce there is presForce+viscForce, derived
# on unpack here) plus the probe's surface-cell count (drives the
# compacted probe's adaptive slot budget, ops/surface.py
# obstacle_probe_budget)
FORCE_PACK = 17


def pack_moments(m: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Momentum-integral dict -> (19,) device vector."""
    return jnp.concatenate([jnp.reshape(m[k], (-1,)) for k in _MOMENT_KEYS])


def unpack_moments(a) -> Dict[str, np.ndarray]:
    a = np.asarray(a, np.float64)
    return {
        "mass": a[0],
        "center": a[1:4],
        "lin_mom": a[4:7],
        "ang_mom": a[7:10],
        "inertia": a[10:19].reshape(3, 3),
    }


def pack_forces(f: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Force-integral dict -> (FORCE_PACK,) device vector.  Band-integral
    producers (force_integrals) lack the probe-only clipped/locomotion
    QoI; those slots pack as 0."""
    z = jnp.zeros((), jnp.result_type(*(jnp.asarray(f[k]).dtype
                                        for k in ("power", "thrust"))))
    return jnp.concatenate(
        [jnp.reshape(jnp.asarray(f.get(k, z)), (-1,)) for k in _FORCE_KEYS]
    )


def unpack_forces(a) -> Dict[str, np.ndarray]:
    a = np.asarray(a, np.float64)
    return {
        "pres_force": a[0:3],
        "visc_force": a[3:6],
        "torque": a[6:9],
        "power": float(a[9]),
        "pout_bnd": float(a[10]),
        "thrust": float(a[11]),
        "drag": float(a[12]),
        "def_power": float(a[13]),
        "def_power_bnd": float(a[14]),
        "p_locom": float(a[15]),
        "n_surf": float(a[16]),
    }


def derived_force_qoi(f: Dict[str, np.ndarray], trans_vel: np.ndarray,
                      eps: float = 1e-21) -> Dict[str, float]:
    """Host-side derived swimming QoI (reference computeForces tail,
    main.cpp:13098-13114): thrust/drag powers and deformation
    efficiencies (EffPDefBnd uses the clipped defPowerBnd, which is
    <= 0 by construction)."""
    vnorm = float(np.linalg.norm(trans_vel))
    pthrust = f["thrust"] * vnorm
    pdrag = f["drag"] * vnorm
    def_power = f["def_power"]
    eff = pthrust / (pthrust - min(def_power, 0.0) + eps)
    eff_bnd = pthrust / (pthrust - f.get("def_power_bnd", 0.0) + eps)
    return {"Pthrust": pthrust, "Pdrag": pdrag, "EffPDef": eff,
            "EffPDefBnd": eff_bnd}


def momentum_integrals_core(x: jnp.ndarray, vol, chi: jnp.ndarray,
                            vel: jnp.ndarray, cm_guess: jnp.ndarray):
    """Layout-generic chi-weighted moments (KernelIntegrateFluidMomenta,
    main.cpp:13625-13735).  x: (..., 3) cell centers; vol: scalar or array
    broadcastable to chi (per-cell volume); works for the dense uniform
    layout and the (nb, bs, bs, bs) AMR block layout alike."""
    w = (chi * vol).reshape(-1)
    xf = x.reshape(-1, 3)
    vf = vel.reshape(-1, 3)
    mass = jnp.sum(w)
    center = w @ xf
    lin = w @ vf
    r = xf - cm_guess
    ang = w @ jnp.cross(r, vf)
    r2 = jnp.sum(r * r, axis=-1)
    eye = jnp.eye(3, dtype=vel.dtype)
    inertia = jnp.sum(w * r2) * eye - jnp.einsum("n,na,nb->ab", w, r, r)
    return {"mass": mass, "center": center, "lin_mom": lin, "ang_mom": ang,
            "inertia": inertia}


def momentum_integrals(grid: UniformGrid, chi: jnp.ndarray, vel: jnp.ndarray,
                       cm_guess: jnp.ndarray):
    """Uniform-grid wrapper of momentum_integrals_core."""
    return momentum_integrals_core(
        grid.cell_centers(vel.dtype), grid.h ** 3, chi, vel, cm_guess
    )


def force_integrals(grid: UniformGrid, chi: jnp.ndarray, p: jnp.ndarray,
                    vel: jnp.ndarray, nu: float, cm: jnp.ndarray,
                    ubody: jnp.ndarray,
                    udef: Optional[jnp.ndarray] = None,
                    vel_unit: Optional[jnp.ndarray] = None):
    """Surface tractions via the chi-gradient surface measure.

    With n_hat the outward normal and delta the surface density,
    grad(chi) = -n_hat * delta, so

      F_pres = integral(-p n_hat) dS      = sum  p * grad_chi * h^3
      F_visc = integral(2 nu S . n_hat)dS = sum -2 nu S . grad_chi * h^3
      power  = integral(traction . u_body) dS

    The swimming split follows the reference per point
    (main.cpp:12476-12485): forcePar = traction . vel_unit, thrust sums
    its positive part, drag its negative part, and def_power is
    traction . u_def (deformation power).

    Reference: ComputeForces probes one-sided stencils at surface points
    (main.cpp:12250-12494); the dense formulation trades its 5h-outside
    probing for the mollified band, consistent with the smoothed chi.
    """
    from cup3d_tpu.ops import stencils as st

    h3 = grid.h ** 3
    gchi = grad_chi(grid, chi)
    up = grid.pad_vector(vel, 1)
    g = [[st.d1_central(up[..., c], 1, a, grid.h) for a in range(3)] for c in range(3)]
    # S_ca = (d_a u_c + d_c u_a)/2
    fpres = jnp.stack(
        [jnp.sum(p * gchi[..., a]) * h3 for a in range(3)]
    )
    fvisc = jnp.stack(
        [
            -nu * jnp.sum(sum((g[c][a] + g[a][c]) * gchi[..., c] for c in range(3)))
            * h3
            for a in range(3)
        ]
    )
    x = grid.cell_centers(vel.dtype)
    r = x - cm
    traction = p[..., None] * gchi - nu * jnp.stack(
        [sum((g[c][a] + g[a][c]) * gchi[..., c] for c in range(3)) for a in range(3)],
        axis=-1,
    )
    torque = jnp.einsum("xyzc->c", jnp.cross(r, traction)) * h3
    power = jnp.sum(traction * ubody) * h3
    return {"pres_force": fpres, "visc_force": fvisc, "torque": torque,
            "power": power,
            **swim_split(traction, h3, udef, vel_unit)}


def vel_unit(v: np.ndarray) -> np.ndarray:
    n = np.linalg.norm(v)
    return v / n if n > 1e-21 else np.zeros(3)


def store_force_qoi(ob, f: Dict[str, np.ndarray]) -> None:
    """Unpacked force vector -> obstacle attributes incl. the derived
    swimming QoI (reference computeForces tail, main.cpp:13098-13114)."""
    ob.pres_force = f["pres_force"]
    ob.visc_force = f["visc_force"]
    ob.force = ob.pres_force + ob.visc_force
    ob.torque = f["torque"]
    ob.pow_out = f["power"]
    ob.pout_bnd = f.get("pout_bnd", 0.0)
    ob.thrust = f["thrust"]
    ob.drag = f["drag"]
    ob.def_power = f["def_power"]
    ob.def_power_bnd = f.get("def_power_bnd", 0.0)
    ob.p_locom = f.get("p_locom", 0.0)
    # measured surface-band size: feeds the compacted probe's adaptive
    # slot budget (ops/surface.obstacle_probe_budget)
    n_surf = f.get("n_surf", 0.0)
    if n_surf > 0:
        ob.n_surf_points = n_surf
    d = derived_force_qoi(f, ob.transVel)
    ob.Pthrust, ob.Pdrag, ob.EffPDef = d["Pthrust"], d["Pdrag"], d["EffPDef"]
    ob.EffPDefBnd = d["EffPDefBnd"]


def log_forces(logger, i: int, time: float, ob) -> None:
    """forces_<i>.txt row: the reference's full per-obstacle QoI set
    (computeForces reduction + derived tail, main.cpp:13089-13114)."""
    logger.write(
        f"forces_{i}.txt",
        f"{time:.8e} " + " ".join(f"{v:.8e}" for v in ob.force)
        + f" {ob.pow_out:.8e} {ob.pout_bnd:.8e} {ob.thrust:.8e}"
        + f" {ob.drag:.8e} {ob.def_power:.8e} {ob.def_power_bnd:.8e}"
        + f" {ob.p_locom:.8e} {ob.EffPDef:.8e} {ob.EffPDefBnd:.8e}\n",
    )


def update_penalization_forces(obstacles, penal_force_fn, vel_new, vel_old,
                               dt, dtype) -> jnp.ndarray:
    """Attach per-obstacle momentum-balance force/torque ON THE BODY
    (reference kernelFinalizePenalizationForce, main.cpp:13913-13938) —
    the negative of the momentum the penalization injects into the fluid,
    so the sign convention matches ob.force from the surface integral.
    Computed every step like the reference.  The (n_obs, 6) result stays
    a device array — rows are attached as lazy slices so the hot loop
    never blocks on a host transfer; consumers that read ob.penal_force
    trigger the (tiny) conversion themselves.  Returns the (n_obs, 6)
    device array so the fast path can fold it into the step's single
    packed read.  CMs come from the device rigid state when this step ran
    rigid_update_device (host mirrors are one update behind there)."""
    def _cm(ob):
        d = ob._dev_rigid
        if d is not None and d["step"] == ob.sim.step:
            return d["cm"]
        return jnp.asarray(ob.centerOfMass, dtype)

    cms = jnp.stack([_cm(ob) for ob in obstacles])
    PF = -penal_force_fn(
        vel_new, vel_old, tuple(ob.chi for ob in obstacles),
        jnp.asarray(dt, dtype), cms,
    )
    for i, ob in enumerate(obstacles):
        ob.penal_force = PF[i, :3]
        ob.penal_torque = PF[i, 3:]
    return PF
