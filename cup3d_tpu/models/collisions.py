"""Obstacle-obstacle collision handling.

Reference: ``preventCollidingObstacles`` + ``ElasticCollision``
(main.cpp:13939-14325).  Per obstacle pair the reference scans cells where
both bodies' SDFs are positive, accumulating the overlap-cell count, the
overlap centroid, each body's mean (normalized) SDF-gradient direction,
and a representative body-point velocity (the max-|u| overlap point); if
the bodies approach along the contact normal it applies an e=1 rigid-body
impulse (with inertia coupling) and latches the resulting velocities for
one step (``collision_counter``, main.cpp:13069-13077).

TPU shape: the overlap scan is one fused masked reduction per pair over
the dense per-obstacle chi fields (chi > 1/2 is the SDF > 0 interior), the
contact direction comes from grad(chi) (same inward orientation as the
reference's SDF gradient), and the tiny 3x3 impulse algebra runs on host
— mirroring the reference's split of grid scan (OpenMP+MPI) vs pair loop
(serial).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

_TOL_CELLS = 0.001  # minimum overlap count (reference `tolerance`)


@jax.jit
def overlap_count(chi_i: jnp.ndarray, chi_j: jnp.ndarray) -> jnp.ndarray:
    """Cheap pre-check: number of cells inside both bodies."""
    return jnp.sum((chi_i > 0.5) & (chi_j > 0.5))


@jax.jit
def pair_overlap_summary(
    chi_i: jnp.ndarray,
    chi_j: jnp.ndarray,
    gchi_i: jnp.ndarray,
    gchi_j: jnp.ndarray,
    ub_i: jnp.ndarray,
    ub_j: jnp.ndarray,
    xc: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """Masked overlap reductions for one obstacle pair.

    chi_*: (...,) characteristic functions; gchi_*: (..., 3) chi gradients;
    ub_*: (..., 3) body-point velocity fields (rigid + deformation);
    xc: (..., 3) cell centers.  The reference accumulates i and j stats
    over the *same* overlap cells (main.cpp:14030-14140), so the count and
    centroid are shared.
    """
    mask = (chi_i > 0.5) & (chi_j > 0.5)
    mf = mask.reshape(-1).astype(chi_i.dtype)
    xf = xc.reshape(-1, 3)
    m = jnp.sum(mf)
    pos = mf @ xf

    def dirsum(g):
        gf = g.reshape(-1, 3)
        n = jnp.sqrt(jnp.sum(gf * gf, axis=-1, keepdims=True)) + 1e-21
        return mf @ (gf / n)

    def rep_vel(ub):
        uf = ub.reshape(-1, 3)
        mag = jnp.sum(uf * uf, axis=-1) * mf
        return uf[jnp.argmax(mag)]

    return {
        "m": m,
        "pos": pos,
        "ivec": dirsum(gchi_i),
        "jvec": dirsum(gchi_j),
        "imom": rep_vel(ub_i),
        "jmom": rep_vel(ub_j),
    }


def _inertia_response(J: np.ndarray, rc: np.ndarray, n: np.ndarray):
    """I^{-1} (rc x n): the angular velocity change per unit impulse
    (reference ComputeJ, main.cpp:13939-13966)."""
    Jm = np.asarray(J, np.float64)
    Jm = Jm + 1e-21 * np.trace(Jm) * np.eye(3) + 1e-30 * np.eye(3)
    return np.linalg.solve(Jm, np.cross(rc, n))


def elastic_collision(m1, m2, J1, J2, v1, v2, o1, o2, c1, c2, n, c, vc1, vc2):
    """e=1 impulse between two rigid bodies (reference ElasticCollision,
    main.cpp:13968-14027).  n: contact normal (i -> j); c: contact point;
    vc1/vc2: representative contact-point velocities.  Returns
    (v1', v2', o1', o2')."""
    e = 1.0
    jr1 = _inertia_response(J1, c - c1, n)
    jr2 = _inertia_response(J2, c - c2, n)
    nom = (1.0 + e) * np.dot(vc1 - vc2, n)
    denom = -(1.0 / m1 + 1.0 / m2) - (
        np.dot(np.cross(jr1, c - c1), n) + np.dot(np.cross(jr2, c - c2), n)
    )
    impulse = nom / (denom + 1e-21)
    return (
        v1 + (n / m1) * impulse,
        v2 - (n / m2) * impulse,
        o1 + jr1 * impulse,
        o2 - jr2 * impulse,
    )


def prevent_colliding_obstacles(
    obstacles: List,
    ubody_fields: List[jnp.ndarray],
    gradchi_fn,
    xc: jnp.ndarray,
    dt: float,
    precheck_counts=None,
) -> bool:
    """Detect overlapping obstacle pairs and resolve them with an elastic
    impulse; latch the collision velocities for one step.  Returns whether
    any collision fired (reference sim.bCollision).

    gradchi_fn: chi -> (..., 3) gradient on the driver's layout.
    precheck_counts: optional {(i, j): float} overlap-cell counts fetched
    by the caller (drivers batch them into another host read); when given,
    the per-pair blocking ``overlap_count`` read is skipped.
    """
    n_obs = len(obstacles)
    if n_obs < 2:
        return False
    # gradients are only needed for pairs that actually overlap; keep the
    # no-contact common case to one cheap masked count per pair
    grads: Dict[int, jnp.ndarray] = {}

    def grad(k):
        if k not in grads:
            grads[k] = gradchi_fn(obstacles[k].chi)
        return grads[k]

    hit = False
    for i in range(n_obs):
        for j in range(i + 1, n_obs):
            oi, oj = obstacles[i], obstacles[j]
            cnt = (
                precheck_counts[(i, j)]
                if precheck_counts is not None
                else float(overlap_count(oi.chi, oj.chi))
            )
            if cnt < _TOL_CELLS:
                continue
            s = pair_overlap_summary(
                oi.chi, oj.chi, grad(i), grad(j),
                ubody_fields[i], ubody_fields[j], xc,
            )
            m = float(s["m"])
            if m < _TOL_CELLS:
                continue
            ivec = np.asarray(s["ivec"], np.float64)
            jvec = np.asarray(s["jvec"], np.float64)
            ni = np.linalg.norm(ivec)
            nj = np.linalg.norm(jvec)
            if ni < 1e-21 or nj < 1e-21:
                continue
            # contact normal: difference of the two inward gradient
            # directions; grad chi points INTO each body, so ivec/ni points
            # from the interface into body i -> n points j -> i
            mvec = ivec / ni - jvec / nj
            mn = np.linalg.norm(mvec)
            if mn < 1e-21:
                continue
            n = mvec / mn
            imom = np.asarray(s["imom"], np.float64)
            jmom = np.asarray(s["jmom"], np.float64)
            # approach test (main.cpp:14262-14266): relative velocity of j
            # w.r.t. i along n must close the gap
            if np.dot(jmom - imom, n) <= 0:
                continue
            hit = True
            c = np.asarray(s["pos"], np.float64) / m
            m1 = oi.mass if oi.mass > 0 else 1.0
            m2 = oj.mass if oj.mass > 0 else 1.0
            # forced bodies are effectively immovable (main.cpp:14293-14298)
            if np.any(oi.bForcedInSimFrame):
                m1 *= 1e10
            if np.any(oj.bForcedInSimFrame):
                m2 *= 1e10
            v1, v2, o1, o2 = elastic_collision(
                m1, m2, oi.J, oj.J, oi.transVel, oj.transVel,
                oi.angVel, oj.angVel, oi.centerOfMass, oj.centerOfMass,
                n, c, imom, jmom,
            )
            for ob, v, o in ((oi, v1, o1), (oj, v2, o2)):
                ob.transVel = np.asarray(v)
                ob.angVel = np.asarray(o)
                ob.collision_vel = np.asarray(v)
                ob.collision_angvel = np.asarray(o)
                ob.collision_counter = 0.01 * dt
    return hit
