"""Sphere obstacle: the simplest concrete body.

Not present in the condensed reference (whose factory only builds StefanFish,
main.cpp:13235-13246) but part of upstream CubismUP_3D's obstacle family;
it exercises the full chi -> penalization -> 6-DOF -> forces pipeline with an
analytic SDF, and flow past a sphere is the classic drag validation.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from cup3d_tpu.models.base import Obstacle


class Sphere(Obstacle):
    def __init__(self, sim, spec):
        super().__init__(sim, spec)
        self.radius = float(spec.get("radius", self.length / 2))
        # the force-probe window is sized from self.length
        # (ops/surface.probe_margin): an explicit radius > length/2 would
        # silently leave surface cells outside the window (dS=0, forces
        # under-measured) — keep length consistent with the actual extent
        # (ADVICE r3, medium)
        self.length = max(self.length, 2.0 * self.radius)

    def rasterize(self, t: float):
        grid = self.sim.grid
        x = grid.cell_centers(self.sim.dtype)
        pos, _ = self.pos_rot_device(self.sim.dtype)
        d = jnp.linalg.norm(x - pos, axis=-1)
        sdf = self.radius - d  # > 0 inside
        return sdf, None
