"""Naca obstacle: rigid extruded NACA airfoil.

Reference: ``NacaMidlineData`` (main.cpp:12749-12810) — a straight midline
along body-x with ``MidlineShapes::naca_width`` as the width profile and a
constant half-height ``L*HoverL/2`` — rasterized by ``PutNacaOnBlocks``
(main.cpp:11740-11926), whose SDF is the *minimum* of the 2-D signed
profile distance in the (x, y) plane and the flat z-slab distance
``height - |z - z0|`` (main.cpp:11834-11837: ``min(signZ*distZ^2,
sign2d*dist1)``).  The reference's factory never constructs it (only
StefanFish, main.cpp:13235-13246); it is provided for upstream parity.

TPU shape: instead of marching surface points per block, every cell of the
dense grid evaluates its distance to the profile polyline with a
``fori_loop`` over boundary segments (the same union-of-segments gather as
the fish rasterizer), using the y-symmetry of the profile to cover both
surfaces with one polyline in the (x, |y|) half-plane.
"""

from __future__ import annotations

import jax
import jax
import jax.numpy as jnp

# position-critical rotation: default bf16-grade matmul precision corrupts
# thin-section SDFs on TPU (see models/fish/rasterize.py)
_HI = jax.lax.Precision.HIGHEST
import numpy as np

from cup3d_tpu.models.base import Obstacle
from cup3d_tpu.models.fish.midline import midline_arc_grid
from cup3d_tpu.models.fish.shapes import naca_width


@jax.jit
def _naca_sdf(points, position, rot, xs, ws, half_height):
    """Signed distance (>0 inside) of computational-frame ``points`` to the
    extruded airfoil: min(signed 2-D profile distance, z-slab distance)."""
    p = jnp.einsum("...c,cd->...d", points - position, rot, precision=_HI)  # body frame
    xb, yb, zb = p[..., 0], jnp.abs(p[..., 1]), p[..., 2]

    # inside test in the (x, |y|) half-plane: under the width graph
    w_at = jnp.interp(xb, xs, ws, left=0.0, right=0.0)
    inside2d = (xb >= xs[0]) & (xb <= xs[-1]) & (yb <= w_at)

    # distance to the profile polyline (x_i, w_i) -- (x_{i+1}, w_{i+1})
    nseg = xs.shape[0] - 1
    big = jnp.asarray(1e10, points.dtype)

    def body(i, dmin):
        x0 = jax.lax.dynamic_index_in_dim(xs, i, keepdims=False)
        x1 = jax.lax.dynamic_index_in_dim(xs, i + 1, keepdims=False)
        w0 = jax.lax.dynamic_index_in_dim(ws, i, keepdims=False)
        w1 = jax.lax.dynamic_index_in_dim(ws, i + 1, keepdims=False)
        ax, ay = x1 - x0, w1 - w0
        alen2 = jnp.maximum(ax * ax + ay * ay, 1e-30)
        t = jnp.clip(((xb - x0) * ax + (yb - w0) * ay) / alen2, 0.0, 1.0)
        dx = xb - (x0 + t * ax)
        dy = yb - (w0 + t * ay)
        return jnp.minimum(dmin, jnp.sqrt(dx * dx + dy * dy + 1e-30))

    dist2d = jax.lax.fori_loop(0, nseg, body, jnp.full(xb.shape, big))
    d2d = jnp.where(inside2d, dist2d, -dist2d)
    dz = half_height - jnp.abs(zb)
    return jnp.minimum(d2d, dz)


class Naca(Obstacle):
    def __init__(self, sim, spec):
        super().__init__(sim, spec)
        self.t_ratio = float(spec.get("tRatio", 0.12))
        self.HoverL = float(spec.get("HoverL", 1.0))
        self.half_height = 0.5 * self.length * self.HoverL
        h = float(np.min(np.asarray(sim.grid.h)))
        rs = midline_arc_grid(self.length, h)
        ws = naca_width(self.t_ratio, self.length, rs)
        dtype = sim.dtype
        # chord centered on the body origin, as the midline-frame fish
        self._xs = jnp.asarray(rs - 0.5 * self.length, dtype)
        self._ws = jnp.asarray(ws, dtype)

    def rasterize(self, t: float):
        grid = self.sim.grid
        dtype = self.sim.dtype
        x = grid.cell_centers(dtype)
        pos, rot = self.pos_rot_device(dtype)
        sdf = _naca_sdf(x, pos, rot, self._xs, self._ws,
                        jnp.asarray(self.half_height, dtype))
        return sdf, None
