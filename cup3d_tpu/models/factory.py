"""Obstacle factory (reference ObstacleFactory, main.cpp:13247-13289):
factory-content lines -> obstacle instances."""

from __future__ import annotations

from typing import Dict, List


def make_obstacles(sim, specs: List[Dict[str, str]]) -> List:
    obstacles = []
    for spec in specs:
        kind = spec["type"].lower()
        if kind == "sphere":
            from cup3d_tpu.models.sphere import Sphere

            obstacles.append(Sphere(sim, spec))
        elif kind == "stefanfish":
            from cup3d_tpu.models.fish import StefanFish

            obstacles.append(StefanFish(sim, spec))
        elif kind == "naca":
            from cup3d_tpu.models.naca import Naca

            obstacles.append(Naca(sim, spec))
        else:
            raise ValueError(f"unknown obstacle type {spec['type']!r}")
    return obstacles
